"""Unified telemetry plane: metrics registry exposition lint, span
trees across the data path, trace-id propagation over storage RPC,
audit-queue overflow accounting, staging-pressure load shedding."""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
import urllib.parse

import pytest

from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.admin import mount_admin
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server
from minio_tpu.utils import telemetry

CREDS = Credentials("telemtestkey", "telemtestsecret1")
REGION = "us-east-1"


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------

def test_registry_families_and_render():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("minio_unit_total", "ops")
    c.inc()
    c.inc(2, api="x")
    g = reg.gauge("minio_unit_gauge", "level")
    g.set(3.5)
    h = reg.histogram("minio_unit_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render()
    assert "minio_unit_total 1" in text
    assert 'minio_unit_total{api="x"} 2' in text
    assert "minio_unit_gauge 3.5" in text
    assert 'minio_unit_seconds_bucket{le="0.1"} 1' in text
    assert 'minio_unit_seconds_bucket{le="+Inf"} 2' in text
    assert "minio_unit_seconds_count 2" in text
    # idempotent getter returns the same family; kind mismatch rejects
    assert reg.counter("minio_unit_total") is c
    with pytest.raises(ValueError):
        reg.gauge("minio_unit_total")
    # invalid names/labels rejected
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        c.inc(1, **{"bad-label": "v"})


def test_span_tree_and_tail_sampling():
    sink = telemetry.SpanSink(capacity=8, slow_s=3600.0, sample=0.0)
    # fast, error-free trace: dropped
    root = telemetry.Span("root", "t1")
    root.finish()
    assert not sink.offer(root)
    # error anywhere in the tree: kept (propagated to the root flag)
    root = telemetry.Span("root", "t2")
    child = telemetry.Span("child", "t2", parent_id=root.span_id,
                           root=root)
    child.mark_error("boom")
    root.add_child(child)
    root.finish()
    assert sink.offer(root)
    # slow trace: kept
    sink.configure(slow_s=0.0)
    root = telemetry.Span("slowroot", "t3")
    root.finish()
    assert sink.offer(root)
    trees = sink.dump()
    assert trees[0]["name"] == "slowroot"          # newest first
    assert trees[1]["children"][0]["error"] == "boom"


def test_span_budget_caps_trace_size(monkeypatch):
    """Past MAX_SPANS per trace, span() degrades to the no-op and the
    root counts the drop — a 10 GiB PUT must not pin 100k Spans."""
    monkeypatch.setattr(telemetry, "MAX_SPANS", 5)
    sink = telemetry.SpanSink(capacity=4, slow_s=0.0, sample=0.0)
    root_cm = telemetry.trace("budget-root")
    with root_cm as root:
        for i in range(10):
            with telemetry.span(f"c{i}"):
                pass
    assert root.n_spans == 5 and root.n_dropped == 5
    assert root.to_dict()["spans_dropped"] == 5
    assert len(root.children) == 5
    del sink


def test_span_noop_without_active_trace():
    assert telemetry.current_span() is None
    with telemetry.span("orphan") as sp:
        assert sp is None                 # no-op: no root, no recording


def test_traced_iter_never_leaks_into_consumer():
    """The stream span is current only while the inner iterator runs —
    between chunks (and after abandonment) the consumer's context is
    untouched (a plain `with span():` in a generator would leak)."""
    sink = telemetry.SpanSink(capacity=4, slow_s=0.0)
    with telemetry._SpanCtx(telemetry.Span("root", "tx"), root=False) \
            as root:
        seen = []

        def chunks():
            seen.append(telemetry.current_span())
            yield b"a"
            seen.append(telemetry.current_span())
            yield b"b"

        it = telemetry.traced_iter("stream", chunks())
        assert next(it) == b"a"
        assert telemetry.current_span() is root      # not the stream span
        it.close()                                    # abandoned mid-read
        assert telemetry.current_span() is root
    assert seen and seen[0] is not root and seen[0].name == "stream"
    assert root.children[0].name == "stream"
    del sink


# ---------------------------------------------------------------------------
# live server: exposition lint + span trees + shed
# ---------------------------------------------------------------------------

class Client:
    def __init__(self, port, creds=CREDS):
        self.port, self.creds = port, creds

    def request(self, method, path, query=None, body=b""):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {"host": f"127.0.0.1:{self.port}"}
        hdrs = sig.sign_v4(method, path, query, hdrs,
                           hashlib.sha256(body).hexdigest(), self.creds,
                           REGION)
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=30)
        conn.request(method, path + (f"?{qs}" if qs else ""), body=body,
                     headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("telemdrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    mount_admin(srv)
    was = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    telemetry.SPANS.configure(sample=1.0)    # keep every trace
    yield srv
    telemetry.SPANS.configure(*was)
    srv.stop()
    sets.close()


@pytest.fixture(scope="module")
def client(server):
    c = Client(server.port)
    assert c.request("PUT", "/telb")[0] == 200
    # multi-batch payload (8-block batches at 64 KiB blocks): the PUT
    # rides the pipelined hot loop, the GET runs the group lookahead
    payload = b"t" * (2 << 20)
    assert c.request("PUT", "/telb/obj", body=payload)[0] == 200
    st, got = c.request("GET", "/telb/obj")
    assert st == 200 and got == payload
    return c


_LINE = r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (?:[0-9eE+.\-]+|\+Inf|NaN)$"


def _parse_exposition(text: str):
    """(families: name -> type, samples: list[sample name]) with
    HELP/TYPE bookkeeping asserted per line."""
    import re
    helped, typed = set(), {}
    samples = []
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped.add(name)
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name not in typed, f"duplicate TYPE for {name}"
            typed[name] = kind
        else:
            m = re.match(_LINE, line)
            assert m, f"malformed sample line: {line!r}"
            samples.append(m.group("name"))
    return helped, typed, samples


def test_metrics_exposition_lint(server, client):
    st, body = client.request("GET", "/minio/prometheus/metrics")
    assert st == 200
    helped, typed, samples = _parse_exposition(body.decode())
    assert samples, "no samples rendered"
    base = {}
    for s in samples:
        fam = s
        for suffix in ("_bucket", "_sum", "_count"):
            if s.endswith(suffix) and s[: -len(suffix)] in typed and \
                    typed.get(s[: -len(suffix)]) == "histogram":
                fam = s[: -len(suffix)]
        base[fam] = base.get(fam, 0) + 1
        # every sample belongs to a family with # HELP and # TYPE
        assert fam in helped, f"sample {s} lacks # HELP"
        assert fam in typed, f"sample {s} lacks # TYPE"
        assert fam.startswith("minio_"), fam
    # histograms expose the full triplet
    for fam, kind in typed.items():
        if kind != "histogram" or fam not in base:
            continue
        assert f"{fam}_sum" in samples and f"{fam}_count" in samples \
            and any(s == f"{fam}_bucket" for s in samples), fam
    # the per-API latency histograms migrated in
    text = body.decode()
    assert typed.get("minio_tpu_http_requests_duration_seconds") == \
        "histogram"
    assert 'minio_tpu_http_requests_duration_seconds_bucket{api="PutObject"' \
        in text
    assert 'api="GetObject"' in text
    assert typed.get("minio_tpu_http_ttfb_seconds") == "histogram"
    # migrated families all present in ONE registry render
    for fam in ("minio_disks_online", "minio_tpu_pipeline_enabled",
                "minio_tpu_pipeline_bpool_waits_total",
                "minio_tpu_sched_queue_depth",
                "minio_tpu_profiler_running",
                "minio_tpu_rpc_calls_total",
                "minio_tpu_audit_dropped_total",
                "minio_tpu_requests_shed_total",
                "minio_heal_mrf_pending"):
        assert fam in typed, fam


def _tree_depth(node: dict) -> int:
    return 1 + max((_tree_depth(c) for c in node.get("children", ())),
                   default=0)


def _find_spans(node: dict, name: str) -> list:
    out = [node] if node["name"] == name else []
    for c in node.get("children", ()):
        out.extend(_find_spans(c, name))
    return out


def test_put_and_get_span_trees(server, client):
    st, body = client.request("GET", "/minio/admin/v3/spans",
                              query={"count": "100"})
    assert st == 200
    spans = json.loads(body)["spans"]
    # the SPANS ring is process-global: filter to THIS module's object
    # (earlier test files leave their own kept traces behind)
    puts = [s for s in spans if s["name"] == "PutObject"
            and s.get("attrs", {}).get("path") == "/telb/obj"]
    gets = [s for s in spans if s["name"] == "GetObject"
            and s.get("attrs", {}).get("path") == "/telb/obj"]
    assert puts and gets
    put, get = puts[-1], gets[-1]
    # handler -> engine -> pipeline stage -> shard I/O
    assert _tree_depth(put) >= 4, json.dumps(put, indent=1)
    assert _find_spans(put, "engine.put_object")
    assert _find_spans(put, "pipeline.encode")
    enc = _find_spans(put, "pipeline.shard_write")
    assert enc and any(_find_spans(e, "disk.shard_write") for e in enc)
    assert _tree_depth(get) >= 4, json.dumps(get, indent=1)
    groups = _find_spans(get, "pipeline.read_group")
    assert groups and any(_find_spans(g, "disk.shard_read")
                          for g in groups)
    # trace ids surfaced on the admin trace entries too
    entries = [e for e in server.api.trace.recent
               if e.get("api") == "PutObject"]
    assert entries and entries[-1].get("trace_id")


def test_slowdown_on_staging_pressure(server, client):
    from minio_tpu.parallel import pipeline as pl
    api = server.api
    shed = telemetry.REGISTRY.counter("minio_tpu_requests_shed_total")
    before = shed.value(reason="staging")
    # simulate BytePool exhaustion (a get() timing out bumps this)
    pool = pl.staging_pool(1 << 12)
    pool.exhausted += 1
    try:
        st, body = client.request("PUT", "/telb/shedme", body=b"x" * 64)
        assert st == 503 and b"SlowDown" in body
        assert shed.value(reason="staging") == before + 1
        # bucket-level ops and reads are never shed
        assert client.request("GET", "/telb/obj")[0] == 200
        # metadata ops on object paths never stage payload: not shed
        tags = (b"<Tagging><TagSet><Tag><Key>k</Key><Value>v</Value>"
                b"</Tag></TagSet></Tagging>")
        st, _ = client.request("PUT", "/telb/obj", body=tags,
                               query={"tagging": ""})
        assert st == 200
    finally:
        # expire the pressure window (the unified admission plane owns
        # the shed state now)
        api.admission._shed_until = 0.0
    assert client.request("PUT", "/telb/shedme", body=b"x" * 64)[0] == 200


# ---------------------------------------------------------------------------
# trace-id propagation across a storage RPC round trip
# ---------------------------------------------------------------------------

def test_trace_id_propagates_across_storage_rpc(tmp_path):
    from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                                   StorageRPCServer)
    from minio_tpu.distributed.transport import RPCServer
    from minio_tpu.storage import new_format_erasure_v3
    from minio_tpu.storage.xl_storage import XLStorage

    fmts = new_format_erasure_v3(1, 1)
    d = XLStorage(str(tmp_path / "rd0"))
    d.write_format(fmts[0][0])
    host = RPCServer().start()
    host.mount(StorageRPCServer({"/rd0": d}, "tracekey",
                                "tracesecret12345").handler)
    remote = RemoteStorage("127.0.0.1", host.port, "/rd0", "tracekey",
                           "tracesecret12345")
    was = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    telemetry.SPANS.configure(sample=1.0)
    try:
        with telemetry.trace("rpc-prop-test") as root:
            remote.make_vol("tv")
            remote.write_all("tv", "x", b"payload")
            assert remote.read_all("tv", "x") == b"payload"
            tid = root.trace_id
        trees = [t for t in telemetry.SPANS.dump(20)
                 if t["trace_id"] == tid]
        assert trees, "trace not kept"
        tree = trees[0]
        # client-side rpc spans in the tree
        client_spans = _find_spans(tree, "rpc.readall")
        assert client_spans
        # the REMOTE side recorded a fragment under the same trace id,
        # grafted beneath the client span that carried the headers
        server_spans = _find_spans(tree, "rpc.server.readall")
        assert server_spans and server_spans[0]["remote"] is True
        assert server_spans[0]["trace_id"] == tid
        assert any(s["span_id"] == server_spans[0].get("parent_id")
                   for s in client_spans)
    finally:
        telemetry.SPANS.configure(*was)
        remote.close()
        host.stop()
        d.close()


# ---------------------------------------------------------------------------
# TraceSys: stream idle timeout + audit overflow accounting
# ---------------------------------------------------------------------------

def test_tracesys_stream_idle_timeout():
    from minio_tpu.s3.trace import TraceSys
    ts = TraceSys()
    t0 = time.perf_counter()
    out = list(ts.stream(idle_timeout=0.3))
    dt = time.perf_counter() - t0
    assert out == []
    assert 0.2 <= dt < 2.0, dt


def test_audit_overflow_drops_and_counts(monkeypatch):
    from minio_tpu.s3.trace import TraceSys
    ts = TraceSys(audit_queue_size=2)
    ts.audit_webhook = "http://127.0.0.1:9/never"
    gate = threading.Event()
    shipped = []

    def slow_ship(entry):
        gate.wait(5.0)
        shipped.append(entry)

    monkeypatch.setattr(ts, "_ship_audit", slow_ship)
    dropped_counter = telemetry.REGISTRY.counter(
        "minio_tpu_audit_dropped_total")
    before = dropped_counter.value()
    for i in range(8):
        ts.record("GET", f"/p{i}", "", 200, 0.001)
    assert ts.requests_total == 8
    assert ts.audit_dropped >= 5          # 1 in flight + 2 queued max
    assert dropped_counter.value() - before == ts.audit_dropped
    gate.set()                            # release the worker
    deadline = time.time() + 5
    while len(shipped) < 8 - ts.audit_dropped and time.time() < deadline:
        time.sleep(0.02)
    # exactly the non-dropped entries ship, on ONE worker thread
    assert len(shipped) == 8 - ts.audit_dropped
    workers = [t for t in threading.enumerate()
               if t.name == "audit-ship"]
    assert len(workers) <= 1


def test_recent_ring_mutation_is_locked():
    """recent.append now happens under _mu with the counters — hammer
    record() from several threads and check ring/counter consistency."""
    from minio_tpu.s3.trace import TraceSys
    ts = TraceSys(ring_size=10_000)

    def spam(n):
        for i in range(n):
            ts.record("GET", f"/r{i}", "", 200, 0.0)

    threads = [threading.Thread(target=spam, args=(500,))
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ts.requests_total == 2000
    assert len(ts.recent) == 2000


# ---------------------------------------------------------------------------
# profiling Kind table + gauges
# ---------------------------------------------------------------------------

def test_profiling_kind_table_and_gauges():
    from minio_tpu.utils import profiling
    assert profiling.parse_kinds(" cpu , mem ,bogus") == ["cpu", "mem"]
    assert profiling.start("bogus") is False
    assert profiling.start("cpu") is True
    try:
        assert profiling.running("cpu") is True
        text = telemetry.REGISTRY.render()
        assert 'minio_tpu_profiler_running{kind="cpu"} 1' in text
        assert 'minio_tpu_profiler_running{kind="mem"} 0' in text
    finally:
        out = profiling.stop_text("cpu")
    assert out is not None and "cumulative" in out
    assert profiling.stop_text("cpu") is None       # already stopped
    text = telemetry.REGISTRY.render()
    assert 'minio_tpu_profiler_running{kind="cpu"} 0' in text


def test_api_name_classifier():
    from minio_tpu.s3.trace import api_name_of
    assert api_name_of("PUT", "/b/k", {}, {}) == "PutObject"
    assert api_name_of("GET", "/b/k", {}, {}) == "GetObject"
    assert api_name_of("PUT", "/b/k", {"partNumber": ["1"],
                                       "uploadId": ["u"]}, {}) == \
        "UploadPart"
    assert api_name_of("POST", "/b/k", {"uploads": [""]}, {}) == \
        "CreateMultipartUpload"
    assert api_name_of("POST", "/b/k", {"uploadId": ["u"]}, {}) == \
        "CompleteMultipartUpload"
    assert api_name_of("GET", "/b", {"list-type": ["2"]}, {}) == \
        "ListObjectsV2"
    assert api_name_of("GET", "/", {}, {}) == "ListBuckets"
    assert api_name_of("PUT", "/b", {}, {}) == "MakeBucket"
    assert api_name_of("DELETE", "/b/k", {}, {}) == "DeleteObject"
    assert api_name_of("GET", "/minio/prometheus/metrics", {}, {}) == \
        "Metrics"
    assert api_name_of("GET", "/minio/admin/v3/info", {}, {}) == "Admin"
