"""IAM: policy evaluation, users/groups/policy mapping, service
accounts, STS AssumeRole, bucket-policy anonymous access — unit level
plus end-to-end over the live S3 server (reference cmd/iam.go,
pkg/iam/policy, cmd/sts-handlers.go test surfaces)."""

from __future__ import annotations

import hashlib
import http.client
import json
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.iam import IAMSys, Policy, PolicyArgs
from minio_tpu.iam.policy import Statement
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("rootiamkey", "rootiamsecretkey")
REGION = "us-east-1"


# ---------------------------------------------------------------------------
# policy document evaluation
# ---------------------------------------------------------------------------

def args(action, bucket="b", obj="", account="alice"):
    return PolicyArgs(account=account, action=action, bucket=bucket,
                      object=obj)


def test_policy_wildcards_and_deny_wins():
    doc = Policy.from_json(json.dumps({
        "Version": "2012-10-17",
        "Statement": [
            {"Effect": "Allow", "Action": "s3:*",
             "Resource": "arn:aws:s3:::b/*"},
            {"Effect": "Deny", "Action": "s3:DeleteObject",
             "Resource": "arn:aws:s3:::b/protected/*"},
        ]}))
    assert doc.is_allowed(args("s3:GetObject", obj="x"))
    assert doc.is_allowed(args("s3:DeleteObject", obj="y"))
    assert not doc.is_allowed(args("s3:DeleteObject", obj="protected/y"))
    # resource outside the allow
    assert not doc.is_allowed(args("s3:GetObject", bucket="other", obj="x"))


def test_policy_bucket_level_actions():
    doc = Policy([Statement("Allow", ["s3:ListBucket"],
                            ["arn:aws:s3:::mybucket"])])
    assert doc.is_allowed(args("s3:ListBucket", bucket="mybucket"))
    assert not doc.is_allowed(args("s3:ListBucket", bucket="nope"))


def test_policy_principal_matching():
    doc = Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow",
                       "Principal": {"AWS": ["*"]},
                       "Action": "s3:GetObject",
                       "Resource": "arn:aws:s3:::pub/*"}]}))
    assert doc.is_allowed(args("s3:GetObject", bucket="pub", obj="o",
                               account="*"))
    assert doc.is_allowed(args("s3:GetObject", bucket="pub", obj="o",
                               account="bob"))


def test_policy_conditions():
    doc = Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                       "Resource": "*",
                       "Condition": {"StringLike":
                                     {"aws:Referer": "*.example.com"}}}]}))
    a = args("s3:GetObject", obj="x")
    assert not doc.is_allowed(a)        # missing condition key
    a.conditions["aws:Referer"] = "www.example.com"
    assert doc.is_allowed(a)


def test_policy_bool_and_negated_absent_key():
    """Bool operator (canonical enforce-TLS deny) + AWS absent-key
    semantics: negated operators are TRUE when the key is missing."""
    deny_http = Policy.from_json(json.dumps({
        "Statement": [
            {"Effect": "Allow", "Action": "s3:*", "Resource": "*"},
            {"Effect": "Deny", "Action": "s3:*", "Resource": "*",
             "Condition": {"Bool": {"aws:SecureTransport": "false"}}}]}))
    a = args("s3:GetObject", obj="x")
    a.conditions["aws:SecureTransport"] = "false"
    assert not deny_http.is_allowed(a)        # plain HTTP: denied
    a.conditions["aws:SecureTransport"] = "true"
    assert deny_http.is_allowed(a)            # TLS: allowed

    hotlink = Policy.from_json(json.dumps({
        "Statement": [
            {"Effect": "Allow", "Action": "s3:GetObject", "Resource": "*"},
            {"Effect": "Deny", "Action": "s3:GetObject", "Resource": "*",
             "Condition": {"StringNotLike":
                           {"aws:Referer": "https://mysite.com/*"}}}]}))
    b = args("s3:GetObject", obj="x")
    # no Referer at all: the negated condition applies -> Deny wins
    assert not hotlink.is_allowed(b)
    b.conditions["aws:Referer"] = "https://evil.example/page"
    assert not hotlink.is_allowed(b)
    b.conditions["aws:Referer"] = "https://mysite.com/gallery"
    assert hotlink.is_allowed(b)


def test_policy_ip_condition_cidr():
    """IpAddress honors the CIDR mask (ADVICE r2: '10.0.1.0/24' must not
    match 10.0.11.x, and '10.0.0.0/8' must match 10.1.2.3)."""
    doc = Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                       "Resource": "*",
                       "Condition": {"IpAddress":
                                     {"aws:SourceIp": "10.0.1.0/24"}}}]}))
    a = args("s3:GetObject", obj="x")
    a.conditions["aws:SourceIp"] = "10.0.1.77"
    assert doc.is_allowed(a)
    a.conditions["aws:SourceIp"] = "10.0.11.77"   # prefix-string trap
    assert not doc.is_allowed(a)
    a.conditions["aws:SourceIp"] = "not-an-ip"
    assert not doc.is_allowed(a)

    wide = Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                       "Resource": "*",
                       "Condition": {"IpAddress":
                                     {"aws:SourceIp": "10.0.0.0/8"}}}]}))
    a.conditions["aws:SourceIp"] = "10.200.1.2"
    assert wide.is_allowed(a)

    neg = Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                       "Resource": "*",
                       "Condition": {"NotIpAddress":
                                     {"aws:SourceIp": "192.168.0.0/16"}}}]}))
    a.conditions["aws:SourceIp"] = "192.168.3.4"
    assert not neg.is_allowed(a)
    a.conditions["aws:SourceIp"] = "10.0.0.1"
    assert neg.is_allowed(a)


# ---------------------------------------------------------------------------
# IAMSys (in-memory)
# ---------------------------------------------------------------------------

def test_iamsys_user_policy_flow():
    iam = IAMSys()
    iam.add_user("alice", "alicesecret123")
    cred = iam.get_credentials("alice")
    assert cred is not None and cred.is_valid()
    # no policy attached: everything denied
    assert not iam.is_allowed(cred, "s3:GetObject", "b", "o")
    iam.attach_policy("readonly", user="alice")
    assert iam.is_allowed(cred, "s3:GetObject", "b", "o")
    assert not iam.is_allowed(cred, "s3:PutObject", "b", "o")
    iam.attach_policy("readwrite", user="alice")
    assert iam.is_allowed(cred, "s3:PutObject", "b", "o")
    # disabled user stops validating
    iam.set_user_status("alice", "off")
    assert not iam.get_credentials("alice").is_valid()


def test_iamsys_group_policy():
    iam = IAMSys()
    iam.add_user("bob", "bobsecret1234")
    iam.add_members_to_group("devs", ["bob"])
    iam.attach_policy("writeonly", group="devs")
    cred = iam.get_credentials("bob")
    assert iam.is_allowed(cred, "s3:PutObject", "b", "o")
    assert not iam.is_allowed(cred, "s3:GetObject", "b", "o")
    iam.remove_members_from_group("devs", ["bob"])
    assert not iam.is_allowed(cred, "s3:PutObject", "b", "o")


def test_iamsys_custom_policy_and_deny():
    iam = IAMSys()
    iam.add_user("carol", "carolsecret12")
    iam.set_policy("nodelete", Policy.from_json(json.dumps({
        "Statement": [
            {"Effect": "Allow", "Action": "s3:*", "Resource": "*"},
            {"Effect": "Deny", "Action": "s3:DeleteObject",
             "Resource": "*"}]})))
    iam.attach_policy("nodelete", user="carol")
    cred = iam.get_credentials("carol")
    assert iam.is_allowed(cred, "s3:PutObject", "b", "o")
    assert not iam.is_allowed(cred, "s3:DeleteObject", "b", "o")


def test_iamsys_service_account_inherits_parent():
    iam = IAMSys()
    iam.add_user("dave", "davesecret123")
    iam.attach_policy("readonly", user="dave")
    svc = iam.new_service_account("dave")
    cred = iam.get_credentials(svc.access_key)
    assert cred.is_service_account()
    assert iam.is_allowed(cred, "s3:GetObject", "b", "o")
    assert not iam.is_allowed(cred, "s3:PutObject", "b", "o")
    # removing the parent kills the service account
    iam.remove_user("dave")
    assert iam.get_credentials(svc.access_key) is None


def test_iamsys_bucket_policy_grants_foreign_user():
    iam = IAMSys()
    iam.add_user("eve", "evesecret1234")
    pol = json.dumps({"Statement": [{
        "Effect": "Allow", "Principal": {"AWS": ["*"]},
        "Action": "s3:GetObject", "Resource": "arn:aws:s3:::open/*"}]})
    iam.bucket_policy_lookup = lambda b: pol if b == "open" else ""
    cred = iam.get_credentials("eve")
    assert iam.is_allowed(cred, "s3:GetObject", "open", "o")
    assert not iam.is_allowed(cred, "s3:PutObject", "open", "o")
    assert not iam.is_allowed(cred, "s3:GetObject", "closed", "o")


# ---------------------------------------------------------------------------
# persistence over a real erasure object layer
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def object_layer(tmp_path_factory):
    root = tmp_path_factory.mktemp("iamdrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    yield sets
    sets.close()


def test_iamsys_persistence_roundtrip(object_layer):
    iam = IAMSys(object_layer, root_cred=CREDS)
    iam.add_user("frank", "franksecret12")
    iam.attach_policy("readwrite", user="frank")
    iam.set_policy("custom1", Policy.from_json(json.dumps({
        "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                       "Resource": "*"}]})))
    iam.add_members_to_group("ops", ["frank"])

    # a fresh IAMSys over the same layer sees everything
    iam2 = IAMSys(object_layer, root_cred=CREDS)
    cred = iam2.get_credentials("frank")
    assert cred is not None
    assert iam2.is_allowed(cred, "s3:PutObject", "b", "o")
    assert "custom1" in iam2.policies
    assert "frank" in iam2.groups["ops"]["members"]

    iam.remove_user("frank")
    iam2.load()
    assert iam2.get_credentials("frank") is None


def test_federated_subject_policy_files_never_collide(object_layer):
    """Advisor r3: 'oidc:a/b' and 'oidc:a_b' must map to distinct
    policy-DB files — lossy '/'→'_' mangling let one identity's policy
    overwrite another's on disk."""
    iam = IAMSys(object_layer, root_cred=CREDS)
    iam.assume_role_with_claims("oidc:a/b", ["readonly"])
    iam.assume_role_with_claims("oidc:a_b", ["readwrite"])
    assert iam.user_policy["oidc:a/b"] == ["readonly"]
    assert iam.user_policy["oidc:a_b"] == ["readwrite"]
    # both mappings survive a reload from disk under their exact subject
    iam2 = IAMSys(object_layer, root_cred=CREDS)
    assert iam2.user_policy["oidc:a/b"] == ["readonly"]
    assert iam2.user_policy["oidc:a_b"] == ["readwrite"]


# ---------------------------------------------------------------------------
# end-to-end over HTTP (signed requests + STS)
# ---------------------------------------------------------------------------

class Client:
    def __init__(self, port, creds):
        self.port, self.creds = port, creds

    def request(self, method, path, query=None, body=b"", sign=True,
                headers=None):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"127.0.0.1:{self.port}"
        if self.creds.session_token:
            hdrs["x-amz-security-token"] = self.creds.session_token
        if sign:
            payload_hash = hashlib.sha256(body).hexdigest()
            hdrs = sig.sign_v4(method, urllib.parse.quote(path), query,
                               hdrs, payload_hash, self.creds, REGION)
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=30)
        conn.request(method, urllib.parse.quote(path) +
                     (f"?{qs}" if qs else ""), body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data


@pytest.fixture(scope="module")
def iam_server(object_layer):
    iam = IAMSys(object_layer, root_cred=CREDS)
    srv = S3Server(object_layer, creds=CREDS, region=REGION,
                   iam=iam).start()
    iam.bucket_policy_lookup = \
        lambda b: srv.api.bucket_meta.get(b).policy_json
    yield srv, iam
    srv.stop()


def test_e2e_user_denied_then_allowed(iam_server):
    srv, iam = iam_server
    root = Client(srv.port, CREDS)
    assert root.request("PUT", "/iambucket")[0] == 200

    iam.add_user("grace", "gracesecret12")
    grace = Client(srv.port, Credentials("grace", "gracesecret12"))
    st, body = grace.request("PUT", "/iambucket/obj", body=b"hi")
    assert st == 403
    iam.attach_policy("readwrite", user="grace")
    st, _ = grace.request("PUT", "/iambucket/obj", body=b"hi")
    assert st == 200
    # readonly downgrade: writes rejected again, reads fine
    iam.attach_policy("readonly", user="grace")
    assert grace.request("PUT", "/iambucket/obj2", body=b"x")[0] == 403
    st, got = grace.request("GET", "/iambucket/obj")
    assert st == 200 and got == b"hi"


def test_e2e_sts_assume_role(iam_server):
    srv, iam = iam_server
    root = Client(srv.port, CREDS)
    iam.add_user("henry", "henrysecret12")
    iam.attach_policy("readwrite", user="henry")
    henry = Client(srv.port, Credentials("henry", "henrysecret12"))

    form = urllib.parse.urlencode({
        "Action": "AssumeRole", "Version": "2011-06-15",
        "DurationSeconds": "1000"}).encode()
    st, body = henry.request("POST", "/", body=form)
    assert st == 200, body
    ns = {"sts": "https://sts.amazonaws.com/doc/2011-06-15/"}
    root_el = ET.fromstring(body)
    creds_el = root_el.find(".//sts:Credentials", ns)
    temp = Credentials(
        access_key=creds_el.find("sts:AccessKeyId", ns).text,
        secret_key=creds_el.find("sts:SecretAccessKey", ns).text,
        session_token=creds_el.find("sts:SessionToken", ns).text)

    tc = Client(srv.port, temp)
    assert root.request("PUT", "/stsbucket")[0] == 200
    assert tc.request("PUT", "/stsbucket/o", body=b"tmp")[0] == 200
    st, got = tc.request("GET", "/stsbucket/o")
    assert st == 200 and got == b"tmp"

    # without the session token the signature is rejected
    naked = Client(srv.port, Credentials(temp.access_key, temp.secret_key))
    assert naked.request("GET", "/stsbucket/o")[0] == 403

    # temp creds cannot re-assume
    assert tc.request("POST", "/", body=form)[0] == 403


def test_e2e_anonymous_via_bucket_policy(iam_server):
    srv, iam = iam_server
    root = Client(srv.port, CREDS)
    assert root.request("PUT", "/pubbucket")[0] == 200
    assert root.request("PUT", "/pubbucket/o", body=b"public")[0] == 200

    anon = Client(srv.port, Credentials())
    assert anon.request("GET", "/pubbucket/o", sign=False)[0] == 403

    pol = json.dumps({"Version": "2012-10-17", "Statement": [{
        "Effect": "Allow", "Principal": {"AWS": ["*"]},
        "Action": ["s3:GetObject"],
        "Resource": ["arn:aws:s3:::pubbucket/*"]}]}).encode()
    assert root.request("PUT", "/pubbucket", query={"policy": ""},
                        body=pol)[0] in (200, 204)
    st, got = anon.request("GET", "/pubbucket/o", sign=False)
    assert st == 200 and got == b"public"
    # anonymous writes still rejected
    assert anon.request("PUT", "/pubbucket/o2", body=b"x",
                        sign=False)[0] == 403

def test_condition_operator_matrix():
    """Numeric/Date/IgnoreCase/Null/IfExists operators (VERDICT r2 weak
    #7: reference pkg/policy/condition matrix breadth)."""
    from minio_tpu.iam.policy import Statement

    def allows(cond, ctx):
        s = Statement(effect="Allow", actions=["s3:GetObject"],
                      resources=["arn:aws:s3:::b/*"], conditions=cond)
        return s.applies(PolicyArgs(account="u", action="s3:GetObject",
                                    bucket="b", object="o",
                                    conditions=ctx))

    # numeric
    c = {"NumericLessThan": {"s3:max-keys": "10"}}
    assert allows(c, {"s3:max-keys": "5"})
    assert not allows(c, {"s3:max-keys": "50"})
    assert not allows(c, {"s3:max-keys": "junk"})   # unparsable: deny
    assert not allows(c, {})                        # absent: deny
    assert allows({"NumericGreaterThanEquals": {"k": "3"}}, {"k": "3"})
    assert allows({"NumericNotEquals": {"k": "3"}}, {"k": "4"})
    assert not allows({"NumericNotEquals": {"k": "3"}}, {"k": "3"})
    assert allows({"NumericNotEquals": {"k": "3"}}, {})  # negated+absent

    # date (ISO and epoch forms)
    c = {"DateGreaterThan": {"aws:CurrentTime": "2026-01-01T00:00:00Z"}}
    assert allows(c, {"aws:CurrentTime": "2026-06-01T00:00:00Z"})
    assert not allows(c, {"aws:CurrentTime": "2025-06-01T00:00:00Z"})
    assert allows({"DateLessThanEquals": {"t": "1700000000"}},
                  {"t": "2023-01-01T00:00:00Z"})

    # case-insensitive string
    c = {"StringEqualsIgnoreCase": {"h": "Alpha"}}
    assert allows(c, {"h": "ALPHA"}) and not allows(c, {"h": "beta"})

    # Null: true = key must be absent, false = present
    assert allows({"Null": {"k": "true"}}, {})
    assert not allows({"Null": {"k": "true"}}, {"k": "x"})
    assert allows({"Null": {"k": "false"}}, {"k": "x"})
    assert not allows({"Null": {"k": "false"}}, {})

    # IfExists: absent key passes, present key must match
    c = {"StringEqualsIfExists": {"k": "v"}}
    assert allows(c, {})
    assert allows(c, {"k": "v"}) and not allows(c, {"k": "w"})

    # unknown operators stay deny-safe
    assert not allows({"MadeUpOperator": {"k": "v"}}, {"k": "v"})
