"""Device scan plane (minio_tpu/scan/): the randomized property suite
pinning BYTE-IDENTITY of the framed SelectObjectContent event stream
between the compiled-kernel device path and the CPU evaluator (the
oracle), plus fallback-reason accounting, scheduler scan-verb
coalescing, and the live HTTP endpoint riding the device path."""

from __future__ import annotations

import csv as _csv
import hashlib
import http.client
import io
import json
import random
import threading
import urllib.parse

import pytest

from minio_tpu.s3select import SelectRequest
from minio_tpu.s3select.select import event_stream
from minio_tpu.scan import ScanEngine
from minio_tpu.scan.plan import Decline, compile_plan
from minio_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _force_device(monkeypatch):
    # the erasure verbs' test discipline: force the kernels onto
    # whatever XLA backend is present (CPU in CI)
    monkeypatch.setenv("MINIO_TPU_SCAN_DEVICE", "force")


# ---------------------------------------------------------------------------
# randomized corpus + query generators (seeded — deterministic in CI)
# ---------------------------------------------------------------------------

_COLS = ("a", "b", "c", "d")
# short pool keeps the pager in the narrow width buckets (fewer jit
# shapes); covers: empty, numeric-looking strings, negatives, floats,
# spaces, case, multi-byte UTF-8
_WORDS = ("", "x", "zz", "abc", "x y", "Par", "10", "-3", "0.5",
          "9", "bé", "Z", "a\nb", "Z\n")
_NUMS = (0, 1, -3, 25, 30, 2.5, -0.5, 10)


def _cell(rng: random.Random):
    r = rng.random()
    if r < 0.45:
        return rng.choice(_NUMS)
    if r < 0.9:
        return rng.choice(_WORDS)
    return None                              # missing / JSON null


def _csv_corpus(rng: random.Random, rows: int) -> bytes:
    out = io.StringIO()
    w = _csv.writer(out)
    w.writerow(_COLS)
    for _ in range(rows):
        cells = [_cell(rng) for _ in _COLS]
        w.writerow(["" if v is None else v for v in cells])
    return out.getvalue().encode()


def _json_corpus(rng: random.Random, rows: int) -> bytes:
    lines = []
    for _ in range(rows):
        row = {}
        for c in _COLS:
            if rng.random() < 0.15:
                continue                     # missing key
            row[c] = _cell(rng)
        lines.append(json.dumps(row))
    return ("\n".join(lines) + "\n").encode()


def _lit(rng: random.Random) -> str:
    if rng.random() < 0.5:
        v = rng.choice(_NUMS)
        return str(v)
    return "'" + rng.choice(_WORDS).replace("'", "") + "'"


def _side(rng: random.Random) -> str:
    r = rng.random()
    if r < 0.45:
        return rng.choice(_COLS)
    if r < 0.75:
        return _lit(rng)
    # arithmetic over a column and a numeric literal
    op = rng.choice("+-*/%")
    return f"({rng.choice(_COLS)} {op} {rng.choice(_NUMS)})"


def _pred(rng: random.Random, depth: int) -> str:
    if depth > 0 and rng.random() < 0.4:
        kind = rng.choice(("and", "or", "not"))
        if kind == "not":
            return f"NOT ({_pred(rng, depth - 1)})"
        return (f"({_pred(rng, depth - 1)}) {kind.upper()} "
                f"({_pred(rng, depth - 1)})")
    kind = rng.random()
    col = rng.choice(_COLS)
    if kind < 0.40:
        op = rng.choice(("=", "!=", "<>", "<", "<=", ">", ">="))
        return f"{_side(rng)} {op} {_side(rng)}"
    if kind < 0.55:
        items = ", ".join(_lit(rng) for _ in range(rng.randint(1, 3)))
        neg = "NOT " if rng.random() < 0.3 else ""
        return f"{col} {neg}IN ({items})"
    if kind < 0.70:
        neg = "NOT " if rng.random() < 0.3 else ""
        return f"{col} {neg}BETWEEN {_lit(rng)} AND {_lit(rng)}"
    if kind < 0.85:
        neg = " NOT" if rng.random() < 0.3 else ""
        return f"{col} IS{neg} NULL"
    needle = rng.choice(("x", "zz", "ab", "P", "0"))
    pat = rng.choice((needle, f"{needle}%", f"%{needle}",
                      f"%{needle}%", "%"))
    neg = "NOT " if rng.random() < 0.3 else ""
    return f"{col} {neg}LIKE '{pat}'"


def _query(rng: random.Random) -> str:
    r = rng.random()
    if r < 0.25:
        proj = "*"
    elif r < 0.5:
        proj = ", ".join(rng.sample(_COLS, rng.randint(1, 3)))
    elif r < 0.65:
        proj = f"{rng.choice(_COLS)} AS v, {rng.choice(_COLS)}"
    else:
        proj = rng.choice(("COUNT(*)",
                           f"COUNT({rng.choice(_COLS)})",
                           f"COUNT(*), COUNT({rng.choice(_COLS)})"))
    q = f"SELECT {proj} FROM S3Object"
    if rng.random() < 0.8:
        q += f" WHERE {_pred(rng, 2)}"
    if rng.random() < 0.25:
        q += f" LIMIT {rng.randint(1, 40)}"
    return q


def _req(expr: str, fmt: str = "CSV", out: str = "CSV",
         json_type: str = "LINES") -> SelectRequest:
    r = SelectRequest()
    r.expression = expr
    r.input_format = fmt
    r.csv_header = "USE"
    r.output_format = out
    r.json_type = json_type
    return r


def _pair(req: SelectRequest, data: bytes) -> tuple[ScanEngine, bytes,
                                                    bytes]:
    """(engine, device-path bytes, CPU-oracle bytes) for one request."""
    eng = ScanEngine()
    dev = b"".join(eng.event_stream(req, data))
    cpu = b"".join(event_stream(req, data))
    return eng, dev, cpu


# ---------------------------------------------------------------------------
# the property: framed output identical, device actually serving
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(4))
def test_property_csv_byte_identity(seed):
    rng = random.Random(1000 + seed)
    data = _csv_corpus(rng, rng.randint(40, 160))
    served = 0
    for _ in range(10):
        expr = _query(rng)
        out = "JSON" if rng.random() < 0.3 else "CSV"
        eng, dev, cpu = _pair(_req(expr, out=out), data)
        assert dev == cpu, expr
        served += eng.device_serves
    # the generator leans supported: the device must carry real traffic
    assert served >= 5


@pytest.mark.parametrize("seed", range(4))
def test_property_json_lines_byte_identity(seed):
    rng = random.Random(2000 + seed)
    data = _json_corpus(rng, rng.randint(40, 160))
    served = 0
    for _ in range(10):
        expr = _query(rng)
        out = "CSV" if rng.random() < 0.3 else "JSON"
        eng, dev, cpu = _pair(_req(expr, fmt="JSON", out=out), data)
        assert dev == cpu, expr
        served += eng.device_serves
    assert served >= 5


def test_semantics_corners_byte_identity():
    """Deterministic corners the randomizer may miss: numeric-vs-string
    coercion, division/modulo by zero, negative floor-mod, empty cells,
    LIMIT mid-chunk, COUNT over nulls."""
    data = (b"a,b,c,d\n"
            b"10,9,x,\n"
            b"-3,0.5,,x y\n"
            b"0,0,Par,10\n"
            b"2.5,-0.5,b\xc3\xa9,Z\n")
    for expr in (
        "SELECT * FROM S3Object WHERE a < '9'",       # mixed coercion
        "SELECT * FROM S3Object WHERE a < b",
        "SELECT * FROM S3Object WHERE (a / b) > 1",   # div by zero row
        "SELECT * FROM S3Object WHERE (a % 2) = 1",   # negative mod
        "SELECT a FROM S3Object WHERE c = ''",
        "SELECT a FROM S3Object WHERE d >= 'Z'",
        "SELECT * FROM S3Object LIMIT 2",
        "SELECT COUNT(*), COUNT(c) FROM S3Object WHERE a <= 10",
        "SELECT a AS x, b FROM S3Object WHERE NOT (a IN (10, '0'))",
        "SELECT * FROM S3Object WHERE b BETWEEN -1 AND 1",
        "SELECT * FROM S3Object WHERE c LIKE '%a%' OR d LIKE 'x%'",
    ):
        eng, dev, cpu = _pair(_req(expr), data)
        assert dev == cpu, expr
        assert eng.device_serves == 1, expr


def test_like_newline_and_empty_pattern_byte_identity():
    """Regex corners the kernel compare can't mirror: LIKE '' is ^$
    (only the EMPTY cell matches, not every row), and '.'/'$' stop at
    newlines inside cells — newline-bearing cells must decline to the
    CPU path, never diverge."""
    jl = (b'{"c": "abc\\n"}\n{"c": "abc"}\n{"c": ""}\n'
          b'{"c": "a\\nb"}\n{"c": "xbc"}\n')
    for expr, fmt, data, served in (
        ("SELECT c FROM S3Object WHERE c LIKE ''", "CSV",
         b"c\nabc\n\nxy\n", True),          # empty pattern, no newlines
        ("SELECT c FROM S3Object WHERE c LIKE 'abc'", "JSON", jl, False),
        ("SELECT c FROM S3Object WHERE c LIKE '%bc'", "JSON", jl, False),
        ("SELECT c FROM S3Object WHERE c LIKE '%b%'", "JSON", jl, False),
        ("SELECT c FROM S3Object WHERE c LIKE '%'", "JSON", jl, False),
    ):
        eng, dev, cpu = _pair(_req(expr, fmt=fmt), data)
        assert dev == cpu, expr
        if served:
            assert eng.device_serves == 1, expr
        else:
            assert eng.fallback_reasons.get("like-newline"), expr


# ---------------------------------------------------------------------------
# fallback: silent, counted by reason, still byte-identical
# ---------------------------------------------------------------------------

def _fallback_counter(reason: str) -> float:
    return telemetry.REGISTRY.counter(
        "minio_tpu_scan_fallbacks_total",
        "Device-scan declines by reason (request fell back "
        "to the CPU evaluator, output identical)").value(reason=reason)


def test_unsupported_constructs_fall_back_counted():
    csv_data = b"a,b\n1,x\n2,y\n"
    nested = b'{"a": {"deep": 1}, "b": 2}\n{"a": 3, "b": 4}\n'
    cases = [
        (_req("SELECT * FROM S3Object WHERE a = 1", fmt="JSON"),
         nested, "nested"),
        (_req("SELECT * FROM S3Object WHERE b LIKE 'a_b'"),
         csv_data, "like-pattern"),
        (_req("SELECT SUM(a) FROM S3Object"), csv_data, "aggregate"),
        (_req("SELECT * FROM S3Object WHERE a = 1", fmt="JSON",
              json_type="DOCUMENT"), b'{"a": 1}', "json-document"),
        (_req("SELECT * FROM S3Object WHERE b = 'x'"),
         b"a,b\n1," + b"w" * 200 + b"\n2,x\n", "wide-string"),
        (_req("SELECT * FROM S3Object WHERE s3object = 'x'"),
         csv_data, "row-ref"),
    ]
    for req, data, reason in cases:
        before = _fallback_counter(reason)
        eng, dev, cpu = _pair(req, data)
        assert dev == cpu, reason
        assert eng.device_serves == 0 and eng.fallbacks == 1, reason
        assert eng.fallback_reasons == {reason: 1}
        assert _fallback_counter(reason) == before + 1


def test_bad_sql_error_parity():
    """A request the parser rejects declines (`sql-error`) and the CPU
    path reproduces the proper S3 error for the client."""
    from minio_tpu.s3.s3errors import S3Error
    eng = ScanEngine()
    with pytest.raises(S3Error):
        b"".join(eng.event_stream(
            _req("SELECT FROM WHERE"), b"a,b\n1,2\n"))
    assert eng.fallback_reasons == {"sql-error": 1}


def test_device_off_falls_back(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_SCAN_DEVICE", "off")
    eng, dev, cpu = _pair(
        _req("SELECT * FROM S3Object WHERE a = 1"), b"a\n1\n2\n")
    assert dev == cpu
    assert eng.device_serves == 0
    assert eng.fallback_reasons == {"no-device": 1}


def test_plan_signature_separates_literals():
    """Differing literals compile DIFFERENT bucket signatures (they are
    baked constants), identical queries share one."""
    from minio_tpu.s3select import sql as _sql
    p1 = compile_plan(_sql.parse(
        "SELECT * FROM S3Object WHERE a = 1"), "CSV")
    p2 = compile_plan(_sql.parse(
        "SELECT * FROM S3Object WHERE a = 2"), "CSV")
    p3 = compile_plan(_sql.parse(
        "SELECT * FROM S3Object WHERE a = 1"), "CSV")
    assert p1.signature != p2.signature
    assert p1.signature == p3.signature
    with pytest.raises(Decline):
        compile_plan(_sql.parse("SELECT AVG(a) FROM S3Object"), "CSV")


# ---------------------------------------------------------------------------
# scheduler scan verb: concurrent requests coalesce into one launch
# ---------------------------------------------------------------------------

def test_concurrent_selects_coalesce_one_launch():
    from minio_tpu.parallel.scheduler import BatchScheduler
    rng = random.Random(77)
    data = _csv_corpus(rng, 120)
    req = _req("SELECT a, b FROM S3Object WHERE a >= 1 AND b <> ''")
    cpu = b"".join(event_stream(req, data))
    # warm the jit cache so the timing window isn't compile-bound
    warm = ScanEngine()
    assert b"".join(warm.event_stream(req, data)) == cpu
    sched = BatchScheduler(max_batch=64, max_wait=0.4)
    try:
        eng = ScanEngine(sched)
        n = 8
        outs: list = [None] * n
        barrier = threading.Barrier(n)

        def one(i: int) -> None:
            barrier.wait()
            outs[i] = b"".join(eng.event_stream(req, data))

        ts = [threading.Thread(target=one, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(o == cpu for o in outs)
        assert eng.device_serves == n
        vs = sched.verb_stats["scan"]
        assert vs["coalesced"] >= 1          # > one request per launch
        assert vs["batches"] < n
        assert vs["blocks"] == n             # one page each
    finally:
        sched.close()


def test_mixed_page_counts_coalesce_correct_slices():
    """Requests with DIFFERENT page counts but one plan/shape coalesce
    into a single launch; each must get exactly its own mask slice
    back (out[at:at+b] distribution + the power-of-two batch pad)."""
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.scan import pager
    rng = random.Random(79)
    small = _csv_corpus(rng, 50)                 # 1 page
    big = _csv_corpus(rng, pager.PAGE_ROWS * 2 + 37)   # 3 pages
    req = _req("SELECT a, b FROM S3Object WHERE a >= 1 AND b <> ''")
    oracles = {d: b"".join(event_stream(req, d)) for d in (small, big)}
    warm = ScanEngine()
    for d in (small, big):                       # jit-warm both shapes
        assert b"".join(warm.event_stream(req, d)) == oracles[d]
    sched = BatchScheduler(max_batch=64, max_wait=0.4)
    try:
        eng = ScanEngine(sched)
        datas = [small, big, big, small]
        outs: list = [None] * len(datas)
        barrier = threading.Barrier(len(datas))

        def one(i: int) -> None:
            barrier.wait()
            outs[i] = b"".join(eng.event_stream(req, datas[i]))

        ts = [threading.Thread(target=one, args=(i,))
              for i in range(len(datas))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i, o in enumerate(outs):
            assert o == oracles[datas[i]], f"stream {i} wrong slice"
        assert eng.device_serves == len(datas)
        vs = sched.verb_stats["scan"]
        assert vs["coalesced"] >= 1              # mixed B coalesced
        assert vs["blocks"] == 8                 # 1+3+3+1 pages
    finally:
        sched.close()


def test_mixed_queries_bucket_separately():
    """Two different plans submitted concurrently stay in separate
    buckets (signature in the key) — no cross-contaminated masks."""
    from minio_tpu.parallel.scheduler import BatchScheduler
    rng = random.Random(78)
    data = _csv_corpus(rng, 90)
    reqs = [_req("SELECT a FROM S3Object WHERE a > 1"),
            _req("SELECT a FROM S3Object WHERE a <= 1")]
    oracles = [b"".join(event_stream(r, data)) for r in reqs]
    sched = BatchScheduler(max_batch=64, max_wait=0.2)
    try:
        eng = ScanEngine(sched)
        outs: list = [None] * 6
        barrier = threading.Barrier(6)

        def one(i: int) -> None:
            barrier.wait()
            outs[i] = b"".join(eng.event_stream(reqs[i % 2], data))

        ts = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i, o in enumerate(outs):
            assert o == oracles[i % 2]
    finally:
        sched.close()


def test_scheduler_close_falls_back():
    """A scan riding a CLOSED former CPU-routes (None result -> the
    Decline('declined') fallback), never hangs or errors."""
    from minio_tpu.parallel.scheduler import BatchScheduler
    sched = BatchScheduler(max_batch=64, max_wait=0.1)
    sched.close()
    eng = ScanEngine(sched)
    req = _req("SELECT * FROM S3Object WHERE a = 1")
    assert b"".join(eng.event_stream(req, b"a\n1\n2\n")) \
        == b"".join(event_stream(req, b"a\n1\n2\n"))
    assert eng.fallback_reasons == {"declined": 1}


# ---------------------------------------------------------------------------
# the live endpoint rides the device path
# ---------------------------------------------------------------------------

def test_select_over_http_device_path(tmp_path):
    from minio_tpu.object.fs import FSObjects
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server

    data = (b"name,age,city\n"
            b"alice,30,paris\n"
            b"bob,25,london\n"
            b"carol,35,paris\n")
    req = _req("SELECT name FROM S3Object WHERE city = 'paris'")
    oracle = b"".join(event_stream(req, data))

    creds = Credentials("scantest1234", "scansecret1234")
    fs = FSObjects(str(tmp_path / "scan"))
    srv = S3Server(fs, creds=creds).start()
    try:
        fs.make_bucket("data")
        fs.put_object("data", "people.csv", data)
        select_xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<SelectObjectContentRequest>"
            "<Expression>SELECT name FROM S3Object "
            "WHERE city = 'paris'</Expression>"
            "<ExpressionType>SQL</ExpressionType>"
            "<InputSerialization><CSV>"
            "<FileHeaderInfo>USE</FileHeaderInfo></CSV>"
            "</InputSerialization>"
            "<OutputSerialization><CSV/></OutputSerialization>"
            "</SelectObjectContentRequest>").encode()
        path = "/data/people.csv"
        query = {"select": [""], "select-type": ["2"]}
        hdrs = {"host": f"127.0.0.1:{srv.port}"}
        hdrs = sig.sign_v4("POST", path, query, hdrs,
                           hashlib.sha256(select_xml).hexdigest(),
                           creds, "us-east-1")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        conn.request("POST", f"{path}?{qs}", body=select_xml,
                     headers=hdrs)
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        assert resp.status == 200
        assert body == oracle                # framed stream, verbatim
        assert srv.api.scan.device_serves == 1
        assert srv.api.scan.fallbacks == 0
    finally:
        srv.stop()
