"""Bitrot hashing tests: known-answer vectors, native<->Python identity,
framing math."""

import hashlib

import numpy as np
import pytest

from minio_tpu import bitrot
from minio_tpu.ops.highwayhash_py import HighwayHash
from minio_tpu.utils import native

HH64_KEY = bytes(range(32))
# Published HighwayHash-64 test vectors (key = 0x00..0x1f as 4 LE u64,
# data = bytes 0..len-1). Lengths 0-7 exercise init/remainder/finalize.
HH64_VECTORS = {
    0: 0x907A56DE22C26E53,
    1: 0x7EAB43AAC7CDDD78,
    2: 0xB8D0569AB0B53D62,
    3: 0x5C6BEFAB8A463D80,
    4: 0xF205A46893007EDA,
    5: 0x2B8A1668E4A94541,
    6: 0xBD4CCC325BEFCA6F,
    7: 0x4D02AE1738F59482,
}

PI_100_DECIMALS = (
    "1415926535897932384626433832795028841971693993751058209749445923078164"
    "062862089986280348253421170679")


class TestHighwayHashPy:
    @pytest.mark.parametrize("n,want", sorted(HH64_VECTORS.items()))
    def test_hh64_vectors(self, n, want):
        h = HighwayHash(HH64_KEY)
        h.update(bytes(range(n)))
        assert h.digest64() == want

    def test_magic_key_derivation(self):
        # The reference's magic bitrot key is HH256(zero_key, pi decimals)
        # (reference constant: cmd/bitrot.go:31). Reproducing it proves
        # byte-identity with the reference's hash library.
        h = HighwayHash(bytes(32))
        h.update(PI_100_DECIMALS.encode())
        assert h.digest256() == bitrot.MAGIC_HIGHWAYHASH_KEY

    def test_streaming_split_invariance(self):
        data = bytes(range(256)) * 5
        h1 = HighwayHash(HH64_KEY)
        h1.update(data)
        h2 = HighwayHash(HH64_KEY)
        for i in range(0, len(data), 37):
            h2.update(data[i:i + 37])
        assert h1.digest256() == h2.digest256()
        assert h1.digest64() == h2.digest64()


@pytest.mark.skipif(not native.available(), reason="native lib unavailable")
class TestNativeHH:
    @pytest.mark.parametrize("n,want", sorted(HH64_VECTORS.items()))
    def test_hh64_vectors(self, n, want):
        assert native.hh64(HH64_KEY, bytes(range(n))) == want

    def test_magic_key_derivation(self):
        got = native.hh256(bytes(32), PI_100_DECIMALS.encode())
        assert got == bitrot.MAGIC_HIGHWAYHASH_KEY

    @pytest.mark.parametrize("n", [0, 1, 31, 32, 33, 63, 64, 100, 1000, 4097])
    def test_native_matches_python(self, n):
        data = bytes((i * 7 + 3) % 256 for i in range(n))
        h = HighwayHash(HH64_KEY)
        h.update(data)
        assert native.hh64(HH64_KEY, data) == h.digest64()
        hp = HighwayHash(bitrot.MAGIC_HIGHWAYHASH_KEY)
        hp.update(data)
        assert native.hh256(bitrot.MAGIC_HIGHWAYHASH_KEY, data) == hp.digest256()

    def test_batch_matches_single(self):
        rng = np.random.default_rng(0)
        shards = rng.integers(0, 256, (16, 1000)).astype(np.uint8)
        got = native.hh256_batch(bitrot.MAGIC_HIGHWAYHASH_KEY, shards)
        for i in range(16):
            want = native.hh256(bitrot.MAGIC_HIGHWAYHASH_KEY,
                                shards[i].tobytes())
            assert got[i].tobytes() == want

    def test_streaming_interface(self):
        data = bytes(range(200))
        h = bitrot._NativeHH256()
        for i in range(0, len(data), 13):
            h.update(data[i:i + 13])
        hp = HighwayHash(bitrot.MAGIC_HIGHWAYHASH_KEY)
        hp.update(data)
        assert h.digest() == hp.digest256()
        # digest() must not consume state: calling twice is stable
        assert h.digest() == hp.digest256()


class TestBitrotLayer:
    def test_algorithm_names_match_reference(self):
        # exact names the reference serializes into xl.meta
        assert {a.value for a in bitrot.BitrotAlgorithm} == {
            "sha256", "blake2b", "highwayhash256", "highwayhash256S"}
        assert bitrot.DEFAULT_BITROT_ALGORITHM.value == "highwayhash256S"
        assert bitrot.BitrotAlgorithm.from_string("sha256") is \
            bitrot.BitrotAlgorithm.SHA256
        with pytest.raises(ValueError):
            bitrot.BitrotAlgorithm.from_string("md5")

    def test_hashers(self):
        data = b"hello bitrot"
        assert bitrot.hash_shard(data, bitrot.BitrotAlgorithm.SHA256) == \
            hashlib.sha256(data).digest()
        assert bitrot.hash_shard(data, bitrot.BitrotAlgorithm.BLAKE2B512) == \
            hashlib.blake2b(data, digest_size=64).digest()
        hh = bitrot.hash_shard(data, bitrot.BitrotAlgorithm.HIGHWAYHASH256S)
        h = HighwayHash(bitrot.MAGIC_HIGHWAYHASH_KEY)
        h.update(data)
        assert hh == h.digest256()

    def test_shard_file_size_math(self):
        a = bitrot.BitrotAlgorithm.HIGHWAYHASH256S
        # one block exactly
        assert bitrot.bitrot_shard_file_size(100, 100, a) == 100 + 32
        # two blocks (one partial)
        assert bitrot.bitrot_shard_file_size(101, 100, a) == 101 + 64
        # whole-file algo: no framing overhead
        assert bitrot.bitrot_shard_file_size(
            101, 100, bitrot.BitrotAlgorithm.SHA256) == 101
        assert bitrot.bitrot_shard_file_size(0, 100, a) == 0

    def test_batch_hash_all_algos(self):
        rng = np.random.default_rng(1)
        shards = rng.integers(0, 256, (4, 257)).astype(np.uint8)
        for algo in bitrot.BitrotAlgorithm:
            got = bitrot.hash_shards_batch(shards, algo)
            assert got.shape == (4, algo.digest_size)
            for i in range(4):
                assert got[i].tobytes() == bitrot.hash_shard(
                    shards[i].tobytes(), algo)
