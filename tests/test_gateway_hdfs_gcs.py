"""HDFS (WebHDFS REST) and GCS (XML/HMAC interop) gateways — the last
two reference gateway kinds (cmd/gateway/{hdfs,gcs}). The HDFS tests
run against an in-process WebHDFS namenode (incl. the two-step
redirected CREATE); the GCS gateway rides the S3 dialect, driven here
against a live endpoint standing in for storage.googleapis.com.
"""

from __future__ import annotations

import http.server
import json
import threading
import urllib.parse

import pytest

from minio_tpu.gateway import new_gateway
from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions


class FakeWebHDFS(http.server.BaseHTTPRequestHandler):
    """WebHDFS v1 subset with namenode->datanode redirect on CREATE
    (the two-step write real clusters require)."""

    fs: dict = {}      # path -> bytes (files); dirs tracked separately
    dirs: set = set()
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _json(self, payload: dict, status: int = 200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _not_found(self, path):
        self._json({"RemoteException": {
            "exception": "FileNotFoundException",
            "message": f"File does not exist: {path}"}}, 404)

    def _dispatch(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(parsed.query).items()}
        path = urllib.parse.unquote(
            parsed.path[len("/webhdfs/v1"):]) or "/"
        op = q.get("op", "").upper()
        n = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(n) if n else b""
        m = self.command

        if m == "PUT" and op == "MKDIRS":
            self.dirs.add(path)
            p = path
            while "/" in p[1:]:
                p = p.rsplit("/", 1)[0]
                self.dirs.add(p)
            return self._json({"boolean": True})
        if m == "PUT" and op == "CREATE":
            if "redirected" not in q:
                # namenode: redirect to the "datanode" (same server)
                self.send_response(307)
                loc = (f"http://127.0.0.1:{self.server.server_address[1]}"
                       f"/webhdfs/v1{urllib.parse.quote(path)}"
                       f"?op=CREATE&redirected=true")
                self.send_header("Location", loc)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return None
            self.fs[path] = body
            return self._json({}, 200)
        if m == "GET" and op == "OPEN":
            if path not in self.fs:
                return self._not_found(path)
            data = self.fs[path]
            off = int(q.get("offset", 0))
            ln = int(q.get("length", len(data) - off))
            out = data[off:off + ln]
            self.send_response(200)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)
            return None
        if m == "GET" and op == "GETFILESTATUS":
            if path in self.fs:
                return self._json({"FileStatus": {
                    "type": "FILE", "length": len(self.fs[path]),
                    "modificationTime": 1700000000000,
                    "pathSuffix": ""}})
            if path in self.dirs:
                return self._json({"FileStatus": {
                    "type": "DIRECTORY", "length": 0,
                    "modificationTime": 1700000000000,
                    "pathSuffix": ""}})
            return self._not_found(path)
        if m == "GET" and op == "LISTSTATUS":
            if path not in self.dirs:
                return self._not_found(path)
            prefix = path.rstrip("/") + "/"
            entries = []
            for d in sorted(self.dirs):
                if d.startswith(prefix) and "/" not in d[len(prefix):] \
                        and d != path:
                    entries.append({"type": "DIRECTORY", "length": 0,
                                    "modificationTime": 1700000000000,
                                    "pathSuffix": d[len(prefix):]})
            for f in sorted(self.fs):
                if f.startswith(prefix) and "/" not in f[len(prefix):]:
                    entries.append({"type": "FILE",
                                    "length": len(self.fs[f]),
                                    "modificationTime": 1700000000000,
                                    "pathSuffix": f[len(prefix):]})
            return self._json({"FileStatuses": {"FileStatus": entries}})
        if m == "DELETE" and op == "DELETE":
            recursive = q.get("recursive") == "true"
            if path in self.fs:
                del self.fs[path]
                return self._json({"boolean": True})
            if path in self.dirs:
                kids = [f for f in list(self.fs) + list(self.dirs)
                        if f.startswith(path + "/")]
                if kids and not recursive:
                    return self._json({"boolean": False})
                for f in kids:
                    self.fs.pop(f, None)
                    self.dirs.discard(f)
                self.dirs.discard(path)
                return self._json({"boolean": True})
            return self._json({"boolean": False})
        return self._json({"RemoteException": {
            "exception": "UnsupportedOperationException",
            "message": op}}, 400)

    do_GET = do_PUT = do_DELETE = _dispatch


@pytest.fixture()
def hdfs_gw():
    FakeWebHDFS.fs = {}
    FakeWebHDFS.dirs = set()
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeWebHDFS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    gw = new_gateway("hdfs", host="127.0.0.1",
                     port=srv.server_address[1])
    yield gw
    srv.shutdown()


def test_hdfs_bucket_and_object_roundtrip(hdfs_gw):
    gw = hdfs_gw
    gw.make_bucket("hb")
    assert gw.bucket_exists("hb")
    assert "hb" in [v.name for v in gw.list_buckets()]
    with pytest.raises(api_errors.BucketExists):
        gw.make_bucket("hb")

    payload = bytes(range(256)) * 100
    info = gw.put_object("hb", "dir/f.bin", payload)
    assert info.size == len(payload)
    got = gw.get_object_info("hb", "dir/f.bin")
    assert got.size == len(payload)
    _i, stream = gw.get_object("hb", "dir/f.bin")
    assert b"".join(stream) == payload
    _i, stream = gw.get_object("hb", "dir/f.bin", offset=10, length=50)
    assert b"".join(stream) == payload[10:60]

    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("hb", "missing")
    gw.delete_object("hb", "dir/f.bin")
    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("hb", "dir/f.bin")
    gw.delete_bucket("hb")
    assert not gw.bucket_exists("hb")


def test_hdfs_listing_and_multipart(hdfs_gw):
    gw = hdfs_gw
    gw.make_bucket("hb")
    for k in ("a/1", "a/2", "b/1", "top"):
        gw.put_object("hb", k, b"x")
    objs, prefixes, _ = gw.list_objects("hb", delimiter="/")
    assert [o.name for o in objs] == ["top"]
    assert sorted(prefixes) == ["a/", "b/"]
    objs, _p, _ = gw.list_objects("hb", prefix="a/")
    assert [o.name for o in objs] == ["a/1", "a/2"]

    from minio_tpu.object import CompletePart
    uid = gw.new_multipart_upload("hb", "mp", None)
    p1 = gw.put_object_part("hb", "mp", uid, 1, b"AA" * 500)
    p2 = gw.put_object_part("hb", "mp", uid, 2, b"BB" * 500)
    info = gw.complete_multipart_upload(
        "hb", "mp", uid, [CompletePart(1, p1.etag),
                          CompletePart(2, p2.etag)])
    _i, stream = gw.get_object("hb", "mp")
    assert b"".join(stream) == b"AA" * 500 + b"BB" * 500


def test_gcs_gateway_rides_xml_hmac_dialect(tmp_path):
    """The GCS gateway speaks the XML/HMAC interop dialect — driven
    against a live endpoint standing in for storage.googleapis.com."""
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    creds = Credentials("gcshmackey12", "gcshmacsecret12")
    drives = [str(tmp_path / f"g{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1,
                                   set_drive_count=4, parity=2,
                                   block_size=1 << 16)
    srv = S3Server(sets, creds=creds).start()
    try:
        gw = new_gateway("gcs", access_key=creds.access_key,
                         secret_key=creds.secret_key,
                         host="127.0.0.1", port=srv.port, secure=False,
                         region="us-east-1")
        assert gw.storage_info()["backend"] == "gateway-gcs-xml"
        gw.make_bucket("gcsb")
        gw.put_object("gcsb", "o", b"gcs data", opts=PutOptions())
        _i, stream = gw.get_object("gcsb", "o")
        assert b"".join(stream) == b"gcs data"
        assert [v.name for v in gw.list_buckets()] == ["gcsb"]
        gw.delete_object("gcsb", "o")
        with pytest.raises(api_errors.ObjectNotFound):
            gw.get_object_info("gcsb", "o")
    finally:
        srv.stop()
        sets.close()


def test_hdfs_delete_nonempty_and_marker_order(hdfs_gw):
    """Review r3: non-empty buckets refuse plain deletes; marker
    pagination uses S3 key order even when a file sorts before a
    sibling directory's subtree."""
    gw = hdfs_gw
    gw.make_bucket("hb2")
    gw.put_object("hb2", "a!", b"bang")       # 'a!' < 'a/b' in S3 order
    gw.put_object("hb2", "a/b", b"sub")
    with pytest.raises(api_errors.BucketNotEmpty):
        gw.delete_bucket("hb2")

    objs, _p, _t = gw.list_objects("hb2")
    assert [o.name for o in objs] == ["a!", "a/b"]
    # paginate 1 at a time across the order boundary
    page1, _p, t1 = gw.list_objects("hb2", max_keys=1)
    assert [o.name for o in page1] == ["a!"] and t1
    page2, _p, _t = gw.list_objects("hb2", marker="a!", max_keys=1)
    assert [o.name for o in page2] == ["a/b"]
    # LIST and HEAD agree on the ETag
    head = gw.get_object_info("hb2", "a/b")
    assert objs[1].etag == head.etag

    gw.delete_object("hb2", "a!")
    gw.delete_object("hb2", "a/b")
    gw.delete_bucket("hb2")                  # now empty: allowed


def test_nats_subject_validation():
    from minio_tpu.features.events import NATSTarget
    with pytest.raises(ValueError):
        NATSTarget("a", "h:4222", "minio events")
    with pytest.raises(ValueError):
        NATSTarget("a", "h:4222", "x\r\nPUB evil 1")
    with pytest.raises(ValueError):
        NATSTarget("a", "h:4222", "")


def test_hdfs_put_etag_matches_head_and_streamed_get(hdfs_gw):
    """Review r3: the PUT-returned ETag must equal HEAD/LIST's, and
    GETs stream instead of materializing (iterator yields chunks)."""
    gw = hdfs_gw
    gw.make_bucket("hb3")
    payload = bytes(range(256)) * 8192        # 2 MiB
    info = gw.put_object("hb3", "big", payload)
    assert info.etag == gw.get_object_info("hb3", "big").etag
    objs, _p, _t = gw.list_objects("hb3")
    assert objs[0].etag == info.etag
    _i, stream = gw.get_object("hb3", "big")
    chunks = list(stream)
    assert len(chunks) >= 2                   # 1 MiB chunking
    assert b"".join(chunks) == payload


def test_hdfs_httpfs_direct_write(tmp_path):
    """An HttpFS-style endpoint that accepts CREATE without redirecting
    must still receive the payload (review r3: the two-step writer sent
    no body on hop 0 and would have written an empty file)."""
    class DirectWebHDFS(FakeWebHDFS):
        def _dispatch(self):
            parsed = urllib.parse.urlsplit(self.path)
            q = {k: v[0] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
            if self.command == "PUT" and \
                    q.get("op", "").upper() == "CREATE":
                path = urllib.parse.unquote(
                    parsed.path[len("/webhdfs/v1"):])
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.fs[path] = self.rfile.read(n) if n else b""
                return self._json({}, 201)      # no redirect
            return super()._dispatch()

    DirectWebHDFS.fs = {}
    DirectWebHDFS.dirs = set()
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                          DirectWebHDFS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        gw = new_gateway("hdfs", host="127.0.0.1",
                         port=srv.server_address[1])
        gw.make_bucket("hb")
        gw.put_object("hb", "direct", b"payload-via-httpfs")
        _i, stream = gw.get_object("hb", "direct")
        assert b"".join(stream) == b"payload-via-httpfs"
    finally:
        srv.shutdown()
