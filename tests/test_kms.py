"""KMS seam for SSE-S3 (VERDICT r3 item 5; reference cmd/crypto/kes.go
+ kms.go): the KES-shaped HTTP client against an in-process fake KMS —
generate/decrypt round trip, context binding, SSE-S3 objects sealed via
the remote DEK, KMS-down failure modes (fail closed, never plaintext),
and config-driven selection of KES over the static key."""

from __future__ import annotations

import base64
import http.server
import json
import os
import threading

import pytest

from minio_tpu.features.kms import KESClient, KMSError, StaticKMS


class FakeKES(http.server.BaseHTTPRequestHandler):
    """KES-shaped fake: /v1/key/generate/<name> mints a DEK sealed by a
    per-key secret XOR pad; /v1/key/decrypt/<name> reverses it. The
    sealed blob embeds the context, so decrypt under a different
    context fails like real KES context binding."""

    keys: dict = {}            # key name -> 32-byte pad
    api_key = "kes-api-key-1"
    calls: list = []
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, status, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        if self.headers.get("Authorization") != f"Bearer {self.api_key}":
            return self._reply(401, {"message": "not authorized"})
        n = int(self.headers.get("Content-Length", 0) or 0)
        try:
            req = json.loads(self.rfile.read(n) or b"{}")
        except ValueError:
            return self._reply(400, {"message": "bad json"})
        parts = self.path.strip("/").split("/")
        if len(parts) != 4 or parts[:2] != ["v1", "key"]:
            return self._reply(404, {"message": "no such route"})
        op, name = parts[2], parts[3]
        FakeKES.calls.append((op, name))
        pad = self.keys.get(name)
        if pad is None:
            return self._reply(404, {"message": f"key {name} not found"})
        ctx = req.get("context", "")
        if op == "generate":
            dek = os.urandom(32)
            sealed = bytes(a ^ b for a, b in zip(dek, pad)) \
                + ctx.encode()
            return self._reply(200, {
                "plaintext": base64.b64encode(dek).decode(),
                "ciphertext": base64.b64encode(sealed).decode()})
        if op == "decrypt":
            try:
                sealed = base64.b64decode(req.get("ciphertext", ""))
            except ValueError:
                return self._reply(400, {"message": "bad ciphertext"})
            if sealed[32:].decode(errors="replace") != ctx:
                return self._reply(400, {"message": "context mismatch"})
            dek = bytes(a ^ b for a, b in zip(sealed[:32], pad))
            return self._reply(200, {
                "plaintext": base64.b64encode(dek).decode()})
        return self._reply(404, {"message": "unknown op"})


@pytest.fixture()
def kes_server():
    FakeKES.keys = {"minio-sse": os.urandom(32)}
    FakeKES.calls = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeKES)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_kes_generate_decrypt_roundtrip(kes_server):
    kms = KESClient(f"http://127.0.0.1:{kes_server}", "minio-sse",
                    api_key=FakeKES.api_key)
    ctx = {"object": "b/key.txt"}
    dek, sealed = kms.generate_key(ctx)
    assert len(dek) == 32 and sealed
    assert kms.decrypt_key(sealed, ctx) == dek
    # context binding: a different context must not unseal
    with pytest.raises(KMSError):
        kms.decrypt_key(sealed, {"object": "b/other"})
    # wrong API key
    bad = KESClient(f"http://127.0.0.1:{kes_server}", "minio-sse",
                    api_key="wrong")
    with pytest.raises(KMSError, match="401"):
        bad.generate_key(ctx)
    # unknown key name
    nk = KESClient(f"http://127.0.0.1:{kes_server}", "ghost",
                   api_key=FakeKES.api_key)
    with pytest.raises(KMSError, match="404"):
        nk.generate_key(ctx)


def test_kes_unreachable_fails_closed():
    kms = KESClient("http://127.0.0.1:1", "minio-sse", timeout=0.5)
    with pytest.raises(KMSError, match="unreachable"):
        kms.generate_key({})
    with pytest.raises(KMSError, match="unreachable"):
        kms.decrypt_key(b"x" * 32, {})
    with pytest.raises(ValueError):
        KESClient("not-a-url", "k")


def test_static_kms_shape():
    master = os.urandom(32)
    kms = StaticKMS(master)
    dek, sealed = kms.generate_key({})
    assert dek == master and sealed == b""
    assert kms.decrypt_key(b"", {}) == master
    with pytest.raises(KMSError):
        kms.decrypt_key(b"some-remote-blob", {})
    with pytest.raises(ValueError):
        StaticKMS(b"short")


def _live_server(tmp_path, kms):
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.server import S3Server
    from tests.test_s3 import CREDS, REGION
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    srv.api.kms = kms
    return srv, sets


def test_sse_s3_through_kes(kes_server, tmp_path):
    """SSE-S3 PUT/GET through the live server with the remote KMS in
    the sealing chain; xl.meta carries the DEK ciphertext, and the
    object survives a KMS outage check (fail closed, then recover)."""
    from tests.test_s3 import S3TestClient
    kms = KESClient(f"http://127.0.0.1:{kes_server}", "minio-sse",
                    api_key=FakeKES.api_key)
    srv, sets = _live_server(tmp_path, kms)
    try:
        c = S3TestClient("127.0.0.1", srv.port)
        assert c.request("PUT", "/kmsbucket")[0] == 200
        payload = os.urandom(120_000)
        st, hdrs, _ = c.request(
            "PUT", "/kmsbucket/sealed", body=payload,
            headers={"x-amz-server-side-encryption": "AES256"})
        assert st == 200
        assert ("generate", "minio-sse") in FakeKES.calls

        # the stored metadata references the remote DEK, and the raw
        # stored bytes are not the plaintext
        from minio_tpu.features import crypto as sse
        md = sets.get_object_info("kmsbucket", "sealed").user_defined
        assert md.get(sse.MK_KMS) == "kes:minio-sse"
        assert md.get(sse.MK_KMS_SEALED)

        st, _, got = c.request("GET", "/kmsbucket/sealed")
        assert st == 200 and got == payload
        assert ("decrypt", "minio-sse") in FakeKES.calls

        # KMS down: GET fails closed with a clean error, no plaintext
        srv.api.kms = KESClient("http://127.0.0.1:1", "minio-sse",
                                timeout=0.3)
        st, _, body = c.request("GET", "/kmsbucket/sealed")
        assert st == 500 and b"KMS" in body
        # PUT of a new SSE object also refuses
        st, _, _ = c.request(
            "PUT", "/kmsbucket/new", body=b"x",
            headers={"x-amz-server-side-encryption": "AES256"})
        assert st == 500
        # KMS back: the object reads again
        srv.api.kms = kms
        st, _, got = c.request("GET", "/kmsbucket/sealed")
        assert st == 200 and got == payload
    finally:
        srv.stop()
        sets.close()


def test_config_selects_kes_over_static(tmp_path, kes_server):
    """kms_kes enable=on replaces the static key at config apply."""
    from minio_tpu.config import ConfigSys
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.server import S3Server
    from tests.test_s3 import CREDS, REGION
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    try:
        cfg = ConfigSys(sets, secret=CREDS.secret_key)
        cfg.set_kv("kms_kes", enable="on",
                   endpoint=f"http://127.0.0.1:{kes_server}",
                   key_name="minio-sse", api_key=FakeKES.api_key)
        cfg.apply(srv.api)
        assert isinstance(srv.api.kms, KESClient)
        assert srv.api.kms.key_name == "minio-sse"
        cfg.set_kv("kms_kes", enable="off")
        cfg.set_kv("kms_secret_key", key="ab" * 32)
        cfg.apply(srv.api)
        assert isinstance(srv.api.kms, StaticKMS)
    finally:
        srv.stop()
        sets.close()
