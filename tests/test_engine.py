"""Erasure object engine tests: PUT/GET/DELETE/LIST, quorum under drive
faults (naughty-disk analog), bitrot reconstruct, multipart, heal —
mirroring the reference's cmd/erasure-object_test.go /
erasure-healing_test.go / erasure-multipart tests."""

import hashlib
import io
import os

import numpy as np
import pytest

from minio_tpu.object import (CompletePart, ErasureSetObjects, GetOptions,
                              PutOptions, api_errors)
from minio_tpu.storage import XLStorage, errors as serr, new_format_erasure_v3
from minio_tpu.storage.naughty import NaughtyDisk

K, M = 4, 2  # small set: fast tests, same code paths as 12+4
NDISKS = K + M
BLOCK = 1 << 16  # 64 KiB blocks keep fixtures fast


def make_engine(tmp_path, n=NDISKS, k=K, m=M, naughty=False):
    fmts = new_format_erasure_v3(1, n)
    disks = []
    for j in range(n):
        d = XLStorage(str(tmp_path / f"d{j}"))
        d.write_format(fmts[0][j])
        disks.append(NaughtyDisk(d) if naughty else d)
    return ErasureSetObjects(disks, k, m, block_size=BLOCK)


@pytest.fixture()
def eng(tmp_path):
    e = make_engine(tmp_path)
    e.make_bucket("bucket")
    return e


@pytest.fixture()
def neng(tmp_path):
    e = make_engine(tmp_path, naughty=True)
    e.make_bucket("bucket")
    return e


def payload(size, seed=7) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


# ---------------------------------------------------------------------------
# basic CRUD
# ---------------------------------------------------------------------------

def test_put_get_roundtrip_sizes(eng):
    for size in [0, 1, 100, BLOCK - 1, BLOCK, BLOCK + 1,
                 3 * BLOCK + 12345]:
        data = payload(size, seed=size)
        oi = eng.put_object("bucket", f"o{size}", data)
        assert oi.size == size
        assert oi.etag == hashlib.md5(data).hexdigest()
        oi2, it = eng.get_object("bucket", f"o{size}")
        assert b"".join(it) == data
        assert oi2.etag == oi.etag


def test_ranged_get(eng):
    data = payload(4 * BLOCK + 999)
    eng.put_object("bucket", "r", data)
    for off, ln in [(0, 10), (BLOCK - 1, 2), (BLOCK, BLOCK),
                    (2 * BLOCK + 7, 3 * BLOCK // 2),
                    (4 * BLOCK + 990, 9), (0, len(data))]:
        _, it = eng.get_object("bucket", "r", offset=off, length=ln)
        assert b"".join(it) == data[off:off + ln], (off, ln)
    with pytest.raises(api_errors.InvalidRange):
        eng.get_object("bucket", "r", offset=len(data) + 1, length=2)


def test_get_missing_object(eng):
    with pytest.raises(api_errors.ObjectNotFound):
        eng.get_object_info("bucket", "nope")
    with pytest.raises(api_errors.BucketNotFound):
        eng.get_object_info("nobucket", "nope")


def test_bucket_lifecycle(eng):
    eng.make_bucket("b2")
    assert eng.bucket_exists("b2")
    with pytest.raises(api_errors.BucketExists):
        eng.make_bucket("b2")
    names = [v.name for v in eng.list_buckets()]
    assert "b2" in names and "bucket" in names
    eng.delete_bucket("b2")
    assert not eng.bucket_exists("b2")
    with pytest.raises(api_errors.BucketNameInvalid):
        eng.make_bucket(".minio.sys")


def test_list_objects_delimiter_and_truncation(eng):
    for name in ["a/1", "a/2", "b/1", "c", "d"]:
        eng.put_object("bucket", name, b"x")
    objs, prefixes, trunc = eng.list_objects("bucket", delimiter="/")
    assert [o.name for o in objs] == ["c", "d"]
    assert prefixes == ["a/", "b/"]
    assert not trunc
    objs, _, _ = eng.list_objects("bucket", prefix="a/")
    assert [o.name for o in objs] == ["a/1", "a/2"]
    objs, prefixes, trunc = eng.list_objects("bucket", max_keys=2)
    assert trunc and len(objs) + len(prefixes) == 2
    # marker resumes
    objs, _, _ = eng.list_objects("bucket", marker="b/1")
    assert [o.name for o in objs] == ["c", "d"]


def test_overwrite_replaces(eng):
    eng.put_object("bucket", "o", b"one")
    eng.put_object("bucket", "o", b"twotwo")
    oi, it = eng.get_object("bucket", "o")
    assert b"".join(it) == b"twotwo"


# ---------------------------------------------------------------------------
# quorum / fault injection
# ---------------------------------------------------------------------------

def test_put_succeeds_with_m_disks_down(neng):
    for d in neng.disks[:M]:
        d.offline = True
    data = payload(2 * BLOCK + 5)
    oi = neng.put_object("bucket", "deg", data)
    for d in neng.disks[:M]:
        d.offline = False
    _, it = neng.get_object("bucket", "deg")
    assert b"".join(it) == data


def test_put_fails_below_write_quorum(neng):
    for d in neng.disks[:M + 1]:
        d.offline = True
    with pytest.raises((api_errors.InsufficientWriteQuorum,
                        api_errors.ObjectApiError)):
        neng.put_object("bucket", "x", payload(BLOCK))


def test_get_with_m_disks_down(neng):
    data = payload(3 * BLOCK + 17)
    neng.put_object("bucket", "o", data)
    for d in neng.disks[K:]:
        d.offline = True  # all parity drives down
    _, it = neng.get_object("bucket", "o")
    assert b"".join(it) == data


def test_get_reconstructs_with_data_disks_down(neng):
    data = payload(3 * BLOCK + 17)
    neng.put_object("bucket", "o", data)
    # distribution maps shard index -> disk; kill two arbitrary drives
    neng.disks[0].offline = True
    neng.disks[3].offline = True
    _, it = neng.get_object("bucket", "o")
    assert b"".join(it) == data


def test_get_fails_below_read_quorum(neng):
    data = payload(BLOCK)
    neng.put_object("bucket", "o", data)
    for d in neng.disks[: M + 1]:
        d.offline = True
    with pytest.raises((api_errors.InsufficientReadQuorum,
                        api_errors.ObjectNotFound)):
        oi, it = neng.get_object("bucket", "o")
        b"".join(it)


def test_read_file_faults_hedge_to_parity(neng):
    data = payload(2 * BLOCK)
    neng.put_object("bucket", "o", data)
    # two drives serve metadata but fail shard reads mid-GET
    neng.disks[1].fail_verbs["read_file_stream"] = serr.FaultyDisk("boom")
    neng.disks[2].fail_verbs["read_file_stream"] = serr.FaultyDisk("boom")
    _, it = neng.get_object("bucket", "o")
    assert b"".join(it) == data


def test_bitrot_corruption_detected_and_recovered(eng, tmp_path):
    data = payload(2 * BLOCK + 3)
    eng.put_object("bucket", "o", data)
    # flip payload bytes in two shard files
    import glob
    parts = sorted(glob.glob(str(tmp_path / "d*" / "bucket" / "o" / "*" /
                                 "part.1")))
    for f in parts[:2]:
        with open(f, "r+b") as fh:
            fh.seek(40)
            fh.write(b"\xff\xff\xff\xff")
    _, it = eng.get_object("bucket", "o")
    assert b"".join(it) == data


def test_group_read_falls_back_to_per_block_hedging(eng, tmp_path):
    """Review r4: distinct readers corrupted at distinct blocks defeat
    group-granular hedging (quorum needs k survivors across the WHOLE
    group) — the read must degrade to per-block hedging, where every
    individual block still has >= k clean shards, and serve the object."""
    data = payload(3 * BLOCK + 7)
    eng.put_object("bucket", "gfb", data)
    fi = eng._read_one("bucket", "gfb")
    dist = fi.erasure.distribution       # drive i holds shard dist[i]-1
    shard_size = -(-BLOCK // K)
    frame = 32 + shard_size              # digest || payload
    import glob
    parts = sorted(glob.glob(str(tmp_path / "d*" / "bucket" / "gfb" /
                                 "*" / "part.1")))

    def corrupt(shard_idx: int, block_idx: int) -> None:
        f = parts[dist.index(shard_idx + 1)]
        with open(f, "r+b") as fh:
            fh.seek(block_idx * frame + 40)   # inside the payload
            fh.write(b"\xff\xff\xff\xff")

    # one DATA shard corrupt at the LAST full block; both PARITY
    # shards corrupt at block 0: a whole-group read loses 3 of 6
    # readers (k=4 group-wide quorum impossible), while per block
    # there are always >= 4 clean shards
    corrupt(0, 2)
    corrupt(K, 0)
    corrupt(K + 1, 0)

    flagged = []
    eng.on_degraded_read = lambda b, o: flagged.append(o)
    _oi, it = eng.get_object("bucket", "gfb")
    assert b"".join(it) == data
    assert "gfb" in flagged              # degraded read queues a heal


def test_delete_missing_object_maps_to_not_found(eng):
    with pytest.raises(api_errors.ObjectNotFound):
        eng.delete_object("bucket", "never-existed")


def test_list_pagination_with_prefix_markers(eng):
    for name in ["a/1", "a/2", "b/1", "c"]:
        eng.put_object("bucket", name, b"x")
    # page 1: one entry
    objs, prefixes, trunc = eng.list_objects("bucket", delimiter="/",
                                             max_keys=1)
    assert trunc and prefixes == ["a/"] and not objs
    # page 2 resumes AFTER prefix 'a/' — must not re-emit it
    objs, prefixes, trunc = eng.list_objects("bucket", delimiter="/",
                                             marker="a/", max_keys=1)
    assert prefixes == ["b/"] and not objs
    objs, prefixes, trunc = eng.list_objects("bucket", delimiter="/",
                                             marker="b/")
    assert [o.name for o in objs] == ["c"] and not prefixes and not trunc


def test_whole_file_bitrot_algo(tmp_path):
    """Engine configured with SHA256 (whole-file) bitrot: digests persist
    per drive in xl.meta, corruption detected and reconstructed."""
    from minio_tpu import bitrot as bm
    fmts = new_format_erasure_v3(1, NDISKS)
    disks = []
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"w{j}"))
        d.write_format(fmts[0][j])
        disks.append(d)
    e = ErasureSetObjects(disks, K, M, block_size=BLOCK,
                          bitrot_algo=bm.BitrotAlgorithm.SHA256)
    e.make_bucket("b")
    data = payload(2 * BLOCK + 7)
    e.put_object("b", "o", data)
    fi = disks[0].read_version("b", "o")
    assert fi.erasure.checksums[0].algorithm == "sha256"
    assert len(fi.erasure.checksums[0].hash) == 32
    disks[0].verify_file("b", "o", fi)
    import glob
    f = glob.glob(str(tmp_path / "w0" / "b" / "o" / "*" / "part.1"))[0]
    with open(f, "r+b") as fh:
        fh.seek(10)
        fh.write(b"Z" * 4)
    _, it = e.get_object("b", "o")
    assert b"".join(it) == data
    res = e.heal_object("b", "o", deep_scan=True)
    assert res.disks_healed == 1


def test_degraded_read_triggers_heal_hook(eng, tmp_path):
    data = payload(BLOCK)
    eng.put_object("bucket", "o", data)
    calls = []
    eng.on_degraded_read = lambda b, o: calls.append((b, o))
    _wipe_drive_object(tmp_path, 0, "bucket", "o")
    _, it = eng.get_object("bucket", "o")
    assert b"".join(it) == data
    assert calls == [("bucket", "o")]


# ---------------------------------------------------------------------------
# multipart
# ---------------------------------------------------------------------------

def test_multipart_roundtrip(eng):
    part_size = 5 << 20
    p1, p2, p3 = payload(part_size, 1), payload(part_size, 2), \
        payload(123456, 3)
    uid = eng.new_multipart_upload("bucket", "mp",
                                   PutOptions(metadata={"content-type":
                                                        "app/x"}))
    uploads = eng.list_multipart_uploads("bucket", "mp")
    assert ("mp", uid) in [(u["object"], u["upload_id"]) for u in uploads]
    etags = []
    for n, p in [(1, p1), (2, p2), (3, p3)]:
        pi = eng.put_object_part("bucket", "mp", uid, n, p)
        assert pi.etag == hashlib.md5(p).hexdigest()
        etags.append(CompletePart(n, pi.etag))
    parts = eng.list_object_parts("bucket", "mp", uid)
    assert [p.part_number for p in parts] == [1, 2, 3]
    oi = eng.complete_multipart_upload("bucket", "mp", uid, etags)
    assert oi.size == 2 * part_size + 123456
    assert oi.etag.endswith("-3")
    want = p1 + p2 + p3
    _, it = eng.get_object("bucket", "mp")
    assert b"".join(it) == want
    # ranged read across part boundary
    off = part_size - 100
    _, it = eng.get_object("bucket", "mp", offset=off, length=200)
    assert b"".join(it) == want[off:off + 200]
    # session is gone
    with pytest.raises(api_errors.InvalidUploadID):
        eng.list_object_parts("bucket", "mp", uid)


def test_multipart_part_reupload_and_abort(eng):
    uid = eng.new_multipart_upload("bucket", "mp2")
    eng.put_object_part("bucket", "mp2", uid, 1, b"aaa")
    pi = eng.put_object_part("bucket", "mp2", uid, 1, b"bbbb")
    parts = eng.list_object_parts("bucket", "mp2", uid)
    assert len(parts) == 1 and parts[0].size == 4
    eng.abort_multipart_upload("bucket", "mp2", uid)
    with pytest.raises(api_errors.InvalidUploadID):
        eng.put_object_part("bucket", "mp2", uid, 2, b"x")


def test_multipart_complete_validation(eng):
    uid = eng.new_multipart_upload("bucket", "mp3")
    pi = eng.put_object_part("bucket", "mp3", uid, 1, b"small")
    with pytest.raises(api_errors.InvalidPart):
        eng.complete_multipart_upload(
            "bucket", "mp3", uid, [CompletePart(1, "wrong-etag")])
    with pytest.raises(api_errors.InvalidPart):
        eng.complete_multipart_upload(
            "bucket", "mp3", uid, [CompletePart(9, pi.etag)])
    # single small part is fine (last part exempt from min size)
    oi = eng.complete_multipart_upload("bucket", "mp3", uid,
                                       [CompletePart(1, pi.etag)])
    assert oi.size == 5


def test_multipart_part_too_small(eng):
    uid = eng.new_multipart_upload("bucket", "mp4")
    p1 = eng.put_object_part("bucket", "mp4", uid, 1, b"tiny")
    p2 = eng.put_object_part("bucket", "mp4", uid, 2, b"tiny2")
    with pytest.raises(api_errors.PartTooSmall):
        eng.complete_multipart_upload(
            "bucket", "mp4", uid,
            [CompletePart(1, p1.etag), CompletePart(2, p2.etag)])


# ---------------------------------------------------------------------------
# healing
# ---------------------------------------------------------------------------

def _wipe_drive_object(tmp_path, di, bucket, obj):
    import shutil
    p = tmp_path / f"d{di}" / bucket / obj
    if p.exists():
        shutil.rmtree(p)


def test_heal_missing_shards(eng, tmp_path):
    data = payload(3 * BLOCK + 99)
    eng.put_object("bucket", "h", data)
    _wipe_drive_object(tmp_path, 0, "bucket", "h")
    _wipe_drive_object(tmp_path, 4, "bucket", "h")

    res = eng.heal_object("bucket", "h")
    assert res.disks_healed == 2
    assert res.missing_after == 0

    # all drives carry verifiable shards again
    for j in range(NDISKS):
        d = eng.disks[j]
        fi = d.read_version("bucket", "h")
        d.check_parts("bucket", "h", fi)
        d.verify_file("bucket", "h", fi)

    # degraded read relying on the healed drives (positions preserved)
    sub = [eng.disks[0], None, None, eng.disks[3], eng.disks[4],
           eng.disks[5]]
    e2 = ErasureSetObjects(sub, K, M, block_size=BLOCK)
    _, it = e2.get_object("bucket", "h")
    assert b"".join(it) == data


def test_heal_corrupt_shard_deep_scan(eng, tmp_path):
    data = payload(2 * BLOCK)
    eng.put_object("bucket", "hc", data)
    import glob
    f = sorted(glob.glob(str(tmp_path / "d2" / "bucket" / "hc" / "*" /
                             "part.1")))[0]
    with open(f, "r+b") as fh:
        fh.seek(50)
        fh.write(b"\x00\x00\x00\x00\x00")

    res = eng.heal_object("bucket", "hc", deep_scan=True)
    assert res.disks_healed == 1
    d = eng.disks[2]
    d.verify_file("bucket", "hc", d.read_version("bucket", "hc"))


def test_heal_dry_run_reports_without_fixing(eng, tmp_path):
    eng.put_object("bucket", "hd", payload(BLOCK))
    _wipe_drive_object(tmp_path, 1, "bucket", "hd")
    res = eng.heal_object("bucket", "hd", dry_run=True)
    assert res.missing_before == 1 and res.disks_healed == 0
    with pytest.raises(serr.StorageError):
        eng.disks[1].read_version("bucket", "hd")


def test_heal_bucket(eng, tmp_path):
    import shutil
    shutil.rmtree(tmp_path / "d3" / "bucket")
    eng.heal_bucket("bucket")
    assert eng.disks[3].stat_vol("bucket").name == "bucket"


def test_heal_delete_marker(eng):
    eng.put_object("bucket", "dm", b"x", opts=PutOptions(versioned=True))
    eng.delete_object("bucket", "dm", versioned=True)
    res = eng.heal_object("bucket", "dm")
    assert res.missing_after == 0


def test_versioned_suspend_and_restore(eng):
    v1 = eng.put_object("bucket", "v", b"v1", opts=PutOptions(versioned=True))
    eng.delete_object("bucket", "v", versioned=True)
    # deleting the delete marker itself restores the object
    versions = eng.list_object_versions("bucket", "v")[0]
    marker = next(v for v in versions if v.delete_marker)
    eng.delete_object("bucket", "v", version_id=marker.version_id)
    oi = eng.get_object_info("bucket", "v")
    assert oi.version_id == v1.version_id


def test_list_versions_quorum_ignores_stale_drive(neng):
    """A drive that missed writes (and a delete) while offline must not
    distort the version history: versions are quorum-merged across the
    per-drive xl.meta journals (VERDICT r2 weak #3; reference
    readAllFileInfo merge, cmd/erasure-metadata-utils.go:118)."""
    v1 = neng.put_object("bucket", "vq", payload(64, 1),
                         opts=PutOptions(versioned=True)).version_id
    neng.disks[0].offline = True
    v2 = neng.put_object("bucket", "vq", payload(64, 2),
                         opts=PutOptions(versioned=True)).version_id
    v3 = neng.put_object("bucket", "vq", payload(64, 3),
                         opts=PutOptions(versioned=True)).version_id
    # v1 removed while the drive is down: its journal still holds v1
    neng.delete_object("bucket", "vq", version_id=v1)
    neng.disks[0].offline = False

    vers = neng.list_object_versions("bucket", "vq")[0]
    ids = {v.version_id for v in vers}
    assert ids == {v2, v3}          # stale v1 gone, offline-era writes in
    # newest first
    assert [v.version_id for v in vers] == [v3, v2]


def test_list_buckets_quorum_merge(neng):
    """Bucket listing survives a stale drive: created-while-offline
    buckets show; deleted-while-offline buckets don't resurrect."""
    neng.disks[0].offline = True
    neng.make_bucket("b-new")
    neng.disks[0].offline = False
    names = [v.name for v in neng.list_buckets()]
    assert "b-new" in names and "bucket" in names

    neng.disks[1].offline = True
    neng.delete_bucket("b-new")
    neng.disks[1].offline = False
    names = [v.name for v in neng.list_buckets()]
    assert "b-new" not in names
