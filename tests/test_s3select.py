"""S3 Select: SQL engine matrix, format readers, event-stream framing,
and the live SelectObjectContent endpoint (reference pkg/s3select test
intents)."""

from __future__ import annotations

import gzip
import hashlib
import http.client
import json
import struct
import urllib.parse
import zlib

import pytest

from minio_tpu.s3select import SelectRequest, run_select
from minio_tpu.s3select.select import event_stream
from minio_tpu.s3select.sql import SQLError, parse

CSV_DATA = (b"name,age,city\n"
            b"alice,30,paris\n"
            b"bob,25,london\n"
            b"carol,35,paris\n"
            b"dave,28,berlin\n")

JSON_LINES = (b'{"name":"alice","age":30}\n'
              b'{"name":"bob","age":25}\n'
              b'{"name":"carol","age":35}\n')


def _req(expr, fmt="CSV", header="USE", out="CSV", compression="NONE",
         json_type="LINES"):
    r = SelectRequest()
    r.expression = expr
    r.input_format = fmt
    r.csv_header = header
    r.output_format = out
    r.compression = compression
    r.json_type = json_type
    return r


def rows(expr, data=CSV_DATA, **kw):
    return b"".join(run_select(_req(expr, **kw), data)).decode()


def test_select_star_where():
    out = rows("SELECT * FROM S3Object WHERE city = 'paris'")
    assert out == "alice,30,paris\r\ncarol,35,paris\r\n".replace(
        "\r\n", "\n") or "alice" in out and "carol" in out \
        and "bob" not in out


def test_select_columns_and_limit():
    out = rows("SELECT name FROM S3Object LIMIT 2")
    assert out.splitlines() == ["alice", "bob"]


def test_select_numeric_comparison_and_arith():
    out = rows("SELECT name, age FROM S3Object WHERE age + 5 >= 35")
    names = [ln.split(",")[0] for ln in out.splitlines()]
    assert names == ["alice", "carol"]


def test_select_aggregates():
    out = rows("SELECT COUNT(*), AVG(age), MIN(age), MAX(age), SUM(age) "
               "FROM S3Object")
    assert out.strip() == "4,29.5,25,35,118"


def test_select_like_in_between():
    assert [ln.split(",")[0] for ln in rows(
        "SELECT name FROM S3Object WHERE name LIKE 'a%'").splitlines()] \
        == ["alice"]
    assert [ln for ln in rows(
        "SELECT name FROM S3Object WHERE city IN ('london', 'berlin')"
    ).splitlines()] == ["bob", "dave"]
    assert [ln for ln in rows(
        "SELECT name FROM S3Object WHERE age BETWEEN 26 AND 31"
    ).splitlines()] == ["alice", "dave"]


def test_select_alias_and_functions():
    out = rows("SELECT UPPER(s.name) AS n FROM S3Object s "
               "WHERE LENGTH(s.name) = 5 AND s.age > 26")
    assert out.splitlines() == ["ALICE", "CAROL"]


def test_select_positional_columns_no_header():
    data = b"x,1\ny,2\n"
    out = rows("SELECT _1 FROM S3Object WHERE CAST(_2 AS int) > 1",
               data=data, header="NONE")
    assert out.strip() == "y"


def test_select_json_lines_and_output_json():
    out = rows("SELECT name, age FROM S3Object WHERE age > 26",
               data=JSON_LINES, fmt="JSON", out="JSON")
    recs = [json.loads(x) for x in out.strip().splitlines()]
    assert recs == [{"name": "alice", "age": 30},
                    {"name": "carol", "age": 35}]


def test_select_json_document():
    doc = json.dumps([{"a": 1}, {"a": 5}]).encode()
    out = rows("SELECT a FROM S3Object WHERE a > 2", data=doc,
               fmt="JSON", json_type="DOCUMENT")
    assert out.strip() == "5"


def test_select_gzip_input():
    out = rows("SELECT COUNT(*) FROM S3Object",
               data=gzip.compress(CSV_DATA), compression="GZIP")
    assert out.strip() == "4"


def test_sql_errors():
    with pytest.raises(SQLError):
        parse("DROP TABLE S3Object")
    with pytest.raises(SQLError):
        parse("SELECT FROM S3Object")
    with pytest.raises(SQLError):
        parse("SELECT * FROM other_table")


# ---------------------------------------------------------------------------
# event-stream framing
# ---------------------------------------------------------------------------

def _parse_events(body: bytes):
    out = []
    i = 0
    while i < len(body):
        total, hlen = struct.unpack_from(">II", body, i)
        pre_crc, = struct.unpack_from(">I", body, i + 8)
        assert pre_crc == zlib.crc32(body[i:i + 8]) & 0xffffffff
        msg_crc, = struct.unpack_from(">I", body, i + total - 4)
        assert msg_crc == zlib.crc32(body[i:i + total - 4]) & 0xffffffff
        headers_raw = body[i + 12:i + 12 + hlen]
        payload = body[i + 12 + hlen:i + total - 4]
        headers = {}
        j = 0
        while j < len(headers_raw):
            nlen = headers_raw[j]
            name = headers_raw[j + 1:j + 1 + nlen].decode()
            assert headers_raw[j + 1 + nlen] == 7
            vlen, = struct.unpack_from(">H", headers_raw, j + 2 + nlen)
            val = headers_raw[j + 4 + nlen:j + 4 + nlen + vlen].decode()
            headers[name] = val
            j += 4 + nlen + vlen
        out.append((headers.get(":event-type"), payload))
        i += total
    return out


def test_event_stream_framing():
    req = _req("SELECT name FROM S3Object LIMIT 1")
    body = b"".join(event_stream(req, CSV_DATA))
    events = _parse_events(body)
    kinds = [k for k, _ in events]
    assert kinds == ["Records", "Stats", "End"]
    assert events[0][1] == b"alice\n"
    assert b"<BytesReturned>6</BytesReturned>" in events[1][1]


# ---------------------------------------------------------------------------
# live endpoint
# ---------------------------------------------------------------------------

def test_select_over_http(tmp_path):
    from minio_tpu.object.fs import FSObjects
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server

    creds = Credentials("selecttest12", "selectsecret12")
    fs = FSObjects(str(tmp_path / "sel"))
    srv = S3Server(fs, creds=creds).start()
    try:
        fs.make_bucket("data")
        fs.put_object("data", "people.csv", CSV_DATA)

        select_xml = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<SelectObjectContentRequest>"
            "<Expression>SELECT name FROM S3Object "
            "WHERE city = 'paris'</Expression>"
            "<ExpressionType>SQL</ExpressionType>"
            "<InputSerialization><CSV>"
            "<FileHeaderInfo>USE</FileHeaderInfo></CSV>"
            "</InputSerialization>"
            "<OutputSerialization><CSV/></OutputSerialization>"
            "</SelectObjectContentRequest>").encode()

        path = "/data/people.csv"
        query = {"select": [""], "select-type": ["2"]}
        hdrs = {"host": f"127.0.0.1:{srv.port}"}
        hdrs = sig.sign_v4("POST", path, query, hdrs,
                           hashlib.sha256(select_xml).hexdigest(), creds,
                           "us-east-1")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        conn.request("POST", f"{path}?{qs}", body=select_xml,
                     headers=hdrs)
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        assert resp.status == 200
        events = _parse_events(body)
        assert [k for k, _ in events] == ["Records", "Stats", "End"]
        assert events[0][1] == b"alice\ncarol\n"
    finally:
        srv.stop()

# ---------------------------------------------------------------------------
# Parquet input (VERDICT r2 item 7; reference pkg/s3select/parquet)
# ---------------------------------------------------------------------------

def _parquet_bytes() -> bytes:
    import io
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    table = pa.table({
        "name": ["alice", "bob", "carol", "dave"],
        "age": [30, 25, 35, 28],
        "city": ["paris", "london", "paris", "berlin"]})
    buf = io.BytesIO()
    pq.write_table(table, buf)
    return buf.getvalue()


def test_select_parquet_matches_csv():
    """The same queries over Parquet and CSV data must agree (CSV
    values are strings, so numeric comparisons go through CAST on the
    CSV side and arrive native from Parquet)."""
    data = _parquet_bytes()
    got = rows("SELECT name FROM S3Object WHERE city = 'paris'",
               data=data, fmt="PARQUET")
    want = rows("SELECT name FROM S3Object WHERE city = 'paris'")
    assert got == want
    got = rows("SELECT name, age FROM S3Object WHERE age > 26",
               data=data, fmt="PARQUET")
    assert got.splitlines() == ["alice,30", "carol,35", "dave,28"]
    got = rows("SELECT COUNT(*), SUM(age) FROM S3Object",
               data=data, fmt="PARQUET")
    assert got.strip() == "4,118"


def test_select_parquet_xml_and_bad_input():
    req = SelectRequest.from_xml(
        b"<SelectObjectContentRequest>"
        b"<Expression>SELECT * FROM S3Object</Expression>"
        b"<ExpressionType>SQL</ExpressionType>"
        b"<InputSerialization><Parquet/></InputSerialization>"
        b"<OutputSerialization><CSV/></OutputSerialization>"
        b"</SelectObjectContentRequest>")
    assert req.input_format == "PARQUET"
    out = b"".join(run_select(req, _parquet_bytes())).decode()
    assert len(out.splitlines()) == 4

    from minio_tpu.s3.s3errors import S3Error
    with pytest.raises(S3Error):
        b"".join(run_select(req, b"this is not parquet"))


def test_select_parquet_event_stream():
    req = SelectRequest.from_xml(
        b"<SelectObjectContentRequest>"
        b"<Expression>SELECT name FROM S3Object WHERE age >= 30"
        b"</Expression><ExpressionType>SQL</ExpressionType>"
        b"<InputSerialization><Parquet/></InputSerialization>"
        b"<OutputSerialization><JSON/></OutputSerialization>"
        b"</SelectObjectContentRequest>")
    frames = b"".join(event_stream(req, _parquet_bytes()))
    assert b'"name": "alice"' in frames or b'"name":"alice"' in frames
    assert b"End" in frames


def test_select_parquet_corrupt_pages_maps_to_s3error():
    """A valid footer with corrupt data pages must raise S3Error from
    the row iterator, not a raw Arrow exception (review r3)."""
    from minio_tpu.s3.s3errors import S3Error
    blob = bytearray(_parquet_bytes())
    # footer (tail) stays intact; clobber the data pages at the front
    for i in range(4, min(60, len(blob) - 100)):
        blob[i] ^= 0xFF
    req = SelectRequest.from_xml(
        b"<SelectObjectContentRequest>"
        b"<Expression>SELECT * FROM S3Object</Expression>"
        b"<ExpressionType>SQL</ExpressionType>"
        b"<InputSerialization><Parquet/></InputSerialization>"
        b"<OutputSerialization><CSV/></OutputSerialization>"
        b"</SelectObjectContentRequest>")
    with pytest.raises(S3Error):
        b"".join(run_select(req, bytes(blob)))
