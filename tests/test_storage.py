"""Storage layer tests: xl.meta journal, format.json quorum, POSIX drive
verbs, bitrot verify (mirrors the reference's xl-storage/xl-meta tests)."""

import io
import os
import uuid

import pytest

from minio_tpu import bitrot
from minio_tpu.storage import (BLOCK_SIZE_V1, FileInfo, FormatErasureV3,
                               XLMetaV2, XLStorage, errors,
                               get_format_in_quorum, hash_order,
                               new_file_info, new_format_erasure_v3)
from minio_tpu.storage.xl_meta import is_xl2_v1_format


# ---------------------------------------------------------------------------
# hash_order (placement-compatibility critical)
# ---------------------------------------------------------------------------

def test_hash_order_reference_vectors():
    # crc32("object")%4 == computed here once; property-level checks:
    order = hash_order("object", 4)
    assert sorted(order) == [1, 2, 3, 4]
    # deterministic
    assert order == hash_order("object", 4)
    # rotation structure: consecutive mod cardinality
    zero = [x - 1 for x in order]
    for i in range(3):
        assert zero[(i + 1)] == (zero[i] + 1) % 4
    assert hash_order("x", 0) == []
    # known value: crc32 of "mybucket/myobject"
    import zlib
    key = "mybucket/myobject"
    start = zlib.crc32(key.encode()) % 16
    got = hash_order(key, 16)
    assert got[0] == 1 + ((start + 1) % 16)


# ---------------------------------------------------------------------------
# xl.meta
# ---------------------------------------------------------------------------

def _sample_fi(version_id="", n_parts=1, deleted=False, mod_time=1000.0):
    fi = new_file_info("bucket/obj", 4, 2)
    fi.volume, fi.name = "bucket", "obj"
    fi.version_id = version_id
    fi.deleted = deleted
    fi.data_dir = str(uuid.uuid4())
    fi.mod_time = mod_time
    fi.size = 1234
    fi.metadata = {"etag": "abc", "content-type": "text/plain",
                   "x-minio-internal-compressed": "s2"}
    for i in range(1, n_parts + 1):
        fi.add_object_part(i, f"etag{i}", 1234, 1234)
    return fi


def test_xlmeta_roundtrip():
    fi = _sample_fi()
    z = XLMetaV2()
    z.add_version(fi)
    buf = z.dumps()
    assert is_xl2_v1_format(buf)
    assert buf[:8] == b"XL2 1   "

    z2 = XLMetaV2.loads(buf)
    got = z2.to_file_info("bucket", "obj")
    assert got.size == 1234
    assert got.data_dir == fi.data_dir
    assert abs(got.mod_time - 1000.0) < 1e-6
    assert got.metadata["etag"] == "abc"
    assert got.metadata["x-minio-internal-compressed"] == "s2"
    assert got.erasure.data_blocks == 4
    assert got.erasure.parity_blocks == 2
    assert got.erasure.distribution == fi.erasure.distribution
    assert got.parts[0].etag == "etag1"
    assert got.is_latest


def test_xlmeta_versions_latest_and_delete_marker():
    z = XLMetaV2()
    v1, v2 = str(uuid.uuid4()), str(uuid.uuid4())
    z.add_version(_sample_fi(v1, mod_time=1000.0))
    z.add_version(_sample_fi(v2, mod_time=2000.0))
    latest = z.to_file_info("bucket", "obj")
    assert latest.version_id == v2 and latest.is_latest
    old = z.to_file_info("bucket", "obj", v1)
    assert old.version_id == v1 and not old.is_latest

    # delete marker becomes latest
    dm = FileInfo(name="obj", version_id=str(uuid.uuid4()),
                  deleted=True, mod_time=3000.0)
    z.add_version(dm)
    latest = z.to_file_info("bucket", "obj")
    assert latest.deleted and latest.is_latest

    # delete a version -> returns its data dir
    dd, last = z.delete_version(FileInfo(name="obj", version_id=v1))
    assert dd and not last
    with pytest.raises(errors.FileVersionNotFound):
        z.to_file_info("bucket", "obj", v1)


def test_xlmeta_null_version():
    z = XLMetaV2()
    z.add_version(_sample_fi(""))  # null version
    fi = z.to_file_info("bucket", "obj", "null")
    assert fi.version_id == ""
    # replacing the null version keeps one entry
    z.add_version(_sample_fi("", mod_time=5000.0))
    assert len(z.versions) == 1


def test_xlmeta_corrupt():
    with pytest.raises(errors.FileCorrupt):
        XLMetaV2.loads(b"garbage-not-xl2-format!")


# ---------------------------------------------------------------------------
# format.json
# ---------------------------------------------------------------------------

def test_format_roundtrip_and_quorum():
    fmts = new_format_erasure_v3(2, 4)
    flat = [f for row in fmts for f in row]
    assert len({f.id for f in flat}) == 1
    assert len({f.this for f in flat}) == 8

    # json round trip
    f0 = FormatErasureV3.from_json(flat[0].to_json())
    assert f0.this == flat[0].this
    assert f0.sets == flat[0].sets
    assert f0.distribution_algo == "SIPMOD"

    # quorum with 3 missing
    ref = get_format_in_quorum(flat[:5] + [None] * 3)
    assert ref.sets == flat[0].sets

    # no quorum
    with pytest.raises(errors.StorageError):
        get_format_in_quorum([flat[0]] + [None] * 7)

    si, di = flat[0].find_disk_index(flat[0].this)
    assert (si, di) == (0, 0)


# ---------------------------------------------------------------------------
# XLStorage drive verbs
# ---------------------------------------------------------------------------

@pytest.fixture()
def drive(tmp_path):
    d = XLStorage(str(tmp_path / "drive0"))
    fmts = new_format_erasure_v3(1, 4)
    d.write_format(fmts[0][0])
    return d


def test_drive_format_identity(drive):
    assert drive.get_disk_id() == drive.read_format().this
    info = drive.disk_info()
    assert info.total > 0 and info.disk_id == drive.get_disk_id()


def test_drive_volumes(drive):
    drive.make_vol("bucket1")
    with pytest.raises(errors.VolumeExists):
        drive.make_vol("bucket1")
    assert "bucket1" in [v.name for v in drive.list_vols()]
    assert drive.stat_vol("bucket1").name == "bucket1"
    with pytest.raises(errors.VolumeNotFound):
        drive.stat_vol("nope")
    drive.write_all("bucket1", "x/y", b"abc")
    with pytest.raises(errors.VolumeNotEmpty):
        drive.delete_vol("bucket1")
    drive.delete_vol("bucket1", force=True)
    with pytest.raises(errors.VolumeNotFound):
        drive.stat_vol("bucket1")


def test_drive_files(drive):
    drive.make_vol("b")
    drive.write_all("b", "dir/file", b"hello world")
    assert drive.read_all("b", "dir/file") == b"hello world"
    with pytest.raises(errors.FileNotFound):
        drive.read_all("b", "missing")
    with pytest.raises(errors.VolumeNotFound):
        drive.read_all("novol", "x")

    # create_file exact-size contract
    drive.create_file("b", "cf", 5, io.BytesIO(b"12345"))
    assert drive.read_all("b", "cf") == b"12345"
    with pytest.raises(errors.LessData):
        drive.create_file("b", "cf2", 10, io.BytesIO(b"123"))
    with pytest.raises(errors.MoreData):
        drive.create_file("b", "cf3", 2, io.BytesIO(b"12345"))

    # append + ranged read
    drive.append_file("b", "ap", b"aaa")
    drive.append_file("b", "ap", b"bbb")
    assert drive.read_file("b", "ap", 2, 3) == b"abb"

    # stream
    r = drive.read_file_stream("b", "ap", 1, 4)
    assert r.read() == b"aabb"
    r.close()

    # rename cleans empty parents
    drive.rename_file("b", "dir/file", "b", "dir2/file2")
    assert not os.path.isdir(os.path.join(drive.root, "b", "dir"))
    assert drive.read_all("b", "dir2/file2") == b"hello world"

    # delete cleans empty parents
    drive.delete_file("b", "dir2/file2")
    assert not os.path.isdir(os.path.join(drive.root, "b", "dir2"))


def test_drive_metadata_roundtrip(drive):
    drive.make_vol("b")
    fi = _sample_fi()
    drive.write_metadata("b", "obj", fi)
    got = drive.read_version("b", "obj")
    assert got.size == fi.size and got.data_dir == fi.data_dir
    versions = drive.read_versions("b", "obj")
    assert len(versions) == 1

    drive.delete_version("b", "obj", got)
    with pytest.raises(errors.FileNotFound):
        drive.read_version("b", "obj")


def test_drive_rename_data_two_phase_commit(drive):
    """Staged tmp write -> RenameData == atomic publish."""
    drive.make_vol("b")
    tmp_vol = ".minio.sys/tmp"
    tmp_id = str(uuid.uuid4())
    fi = _sample_fi()
    # stage: shard + xl.meta under tmp
    drive.write_all(tmp_vol, f"{tmp_id}/{fi.data_dir}/part.1", b"shard-bytes")
    drive.write_metadata(tmp_vol, tmp_id, fi)

    drive.rename_data(tmp_vol, tmp_id, fi.data_dir, "b", "obj")
    got = drive.read_version("b", "obj")
    assert got.data_dir == fi.data_dir
    assert drive.read_all("b", f"obj/{fi.data_dir}/part.1") == b"shard-bytes"
    # tmp is gone
    with pytest.raises(errors.FileNotFound):
        drive.read_all(tmp_vol, f"{tmp_id}/{fi.data_dir}/part.1")

    # overwrite via second rename_data replaces the null version
    fi2 = _sample_fi(mod_time=2000.0)
    tmp_id2 = str(uuid.uuid4())
    drive.write_all(tmp_vol, f"{tmp_id2}/{fi2.data_dir}/part.1", b"v2")
    drive.write_metadata(tmp_vol, tmp_id2, fi2)
    drive.rename_data(tmp_vol, tmp_id2, fi2.data_dir, "b", "obj")
    got2 = drive.read_version("b", "obj")
    assert got2.data_dir == fi2.data_dir
    assert len(drive.read_versions("b", "obj")) == 1  # null replaced


def test_drive_walk(drive):
    drive.make_vol("b")
    for name in ["a/1", "a/2", "z"]:
        fi = _sample_fi()
        tmp_id = str(uuid.uuid4())
        drive.write_all(".minio.sys/tmp",
                        f"{tmp_id}/{fi.data_dir}/part.1", b"x")
        drive.write_metadata(".minio.sys/tmp", tmp_id, fi)
        drive.rename_data(".minio.sys/tmp", tmp_id, fi.data_dir, "b", name)
    names = [fi.name for fi in drive.walk("b")]
    assert names == ["a/1", "a/2", "z"]
    names = [fi.name for fi in drive.walk("b", dir_path="a")]
    assert names == ["a/1", "a/2"]


def test_drive_verify_file_streaming_bitrot(drive, tmp_path):
    """Streaming framing [digest||block]* round-trips through verify and a
    flipped byte is caught (reference bitrotVerify)."""
    drive.make_vol("b")
    algo = bitrot.DEFAULT_BITROT_ALGORITHM
    fi = new_file_info("b/o", 4, 2)
    fi.volume, fi.name = "b", "o"
    fi.data_dir = str(uuid.uuid4())
    fi.erasure.block_size = 1024  # small blocks for the test
    part_size = fi.erasure.shard_file_size(4096)
    shard_size = fi.erasure.shard_size()
    fi.size = 4096
    fi.add_object_part(1, "", 4096, 4096)
    fi.erasure.checksums = []
    from minio_tpu.storage.datatypes import ChecksumInfo
    fi.erasure.checksums.append(ChecksumInfo(1, algo.value, b""))

    # build a framed shard file: per block digest||block
    payload = os.urandom(part_size)
    framed = b""
    off = 0
    while off < part_size:
        blk = payload[off:off + shard_size]
        framed += bitrot.hash_shard(blk, algo) + blk
        off += shard_size
    drive.write_all("b", f"o/{fi.data_dir}/part.1", framed)

    drive.verify_file("b", "o", fi)   # passes
    drive.check_parts("b", "o", fi)   # sizes ok

    # flip one payload byte -> mismatch
    bad = bytearray(framed)
    bad[algo.digest_size + 3] ^= 0xFF
    drive.write_all("b", f"o/{fi.data_dir}/part.1", bytes(bad))
    with pytest.raises(errors.BitrotHashMismatch):
        drive.verify_file("b", "o", fi)


def test_drive_path_traversal_rejected(drive):
    drive.make_vol("b")
    for bad in ["../x", "a/../../x", "/etc/passwd", "..\\x"]:
        with pytest.raises(errors.FileAccessDenied):
            drive.read_all("b", bad)
    with pytest.raises(errors.FileAccessDenied):
        drive.delete_file("b", "../../outside", recursive=True)
    with pytest.raises((errors.FileAccessDenied, errors.VolumeNotFound)):
        drive.stat_vol("../escape")


def test_shard_file_math():
    fi = new_file_info("x", 12, 4)
    ei = fi.erasure
    assert ei.block_size == BLOCK_SIZE_V1
    ss = ei.shard_size()
    assert ss == -(-BLOCK_SIZE_V1 // 12)
    # one full block
    assert ei.shard_file_size(BLOCK_SIZE_V1) == ss
    # block + 1 byte
    assert ei.shard_file_size(BLOCK_SIZE_V1 + 1) == ss + 1
    assert ei.shard_file_size(0) == 0
    # offset never exceeds file size
    total = 3 * BLOCK_SIZE_V1 + 17
    assert ei.shard_file_offset(0, total, total) == ei.shard_file_size(total)


# ---------------------------------------------------------------------------
# O_DIRECT drive path (VERDICT r3 item 6; cmd/xl-storage.go:1664 +
# cmd/fallocate_linux.go)
# ---------------------------------------------------------------------------

def test_direct_io_aligned_writer_roundtrip(tmp_path):
    """The O_DIRECT appender produces byte-identical files across
    alignment edge cases (page-multiple, sub-page tail, tiny writes)."""
    import minio_tpu.storage.xl_storage as xs
    drive = xs.XLStorage(str(tmp_path / "d"), direct_io=True)
    drive.make_vol("v")
    cases = {
        "empty": [b""],
        "subpage": [b"a" * 4095],
        "page": [b"b" * 4096],
        "page_plus": [b"c" * 4097],
        "frames": [b"\x01" * 32, b"\x02" * 87382,
                   b"\x03" * 32, b"\x04" * 87382],
        "big": [bytes(range(256)) * 5000],          # 1.28 MB > BUF
    }
    for name, chunks in cases.items():
        w = drive.open_appender("v", name)
        for c in chunks:
            w.write(c)
        w.close()
        assert drive.read_all("v", name) == b"".join(chunks), name
    # the direct path really engaged on this filesystem (ext4 /tmp) —
    # unless the fs refuses O_DIRECT, in which case fallback is the
    # point being tested elsewhere
    w = drive.open_appender("v", "probe")
    engaged = isinstance(w, xs._DirectWriter)
    w.close()
    import os as _os
    # ext4 supports O_DIRECT; only skip the engagement assert on
    # filesystems that don't
    try:
        fd = _os.open(str(tmp_path / "o_direct_probe"),
                      _os.O_WRONLY | _os.O_CREAT | _os.O_DIRECT)
        _os.close(fd)
        supports = True
    except OSError:
        supports = False
    assert engaged == supports


def test_direct_io_appender_appends_like_buffered(tmp_path):
    """Review r4: open_appender must APPEND under direct IO exactly as
    the buffered path does — aligned existing sizes go direct, an
    unaligned existing file falls back to buffered append, and nothing
    ever truncates."""
    import minio_tpu.storage.xl_storage as xs
    drive = xs.XLStorage(str(tmp_path / "d"), direct_io=True)
    drive.make_vol("v")
    # aligned existing content (one page): direct append is legal
    w = drive.open_appender("v", "f")
    w.write(b"a" * 4096)
    w.close()
    w = drive.open_appender("v", "f")
    w.write(b"b" * 100)
    w.close()
    assert drive.read_all("v", "f") == b"a" * 4096 + b"b" * 100
    # now unaligned: a further appender must NOT truncate or misalign
    w = drive.open_appender("v", "f")
    assert not isinstance(w, xs._DirectWriter)
    w.write(b"c")
    w.close()
    assert drive.read_all("v", "f") == b"a" * 4096 + b"b" * 100 + b"c"


def test_direct_io_fallback_when_fs_refuses(tmp_path, monkeypatch):
    """Filesystems without O_DIRECT (older tmpfs, some network FS)
    refuse at open: the drive must degrade to buffered IO, not fail.
    Simulated deterministically — modern kernels accept O_DIRECT even
    on tmpfs, so a real mount can't pin this behavior."""
    import io as _io
    import minio_tpu.storage.xl_storage as xs

    class Refuses(xs._DirectWriter):
        def __init__(self, path, truncate=True):
            raise OSError(22, "Invalid argument")

    monkeypatch.setattr(xs, "_DirectWriter", Refuses)
    drive = xs.XLStorage(str(tmp_path / "d"), direct_io=True)
    drive.make_vol("v")
    w = drive.open_appender("v", "f")
    assert isinstance(w, _io.IOBase)      # plain buffered file
    w.write(b"payload")
    w.close()
    assert drive.read_all("v", "f") == b"payload"
    drive.create_file("v", "cf", 5000, _io.BytesIO(b"z" * 5000))
    assert drive.read_all("v", "cf") == b"z" * 5000


def test_direct_io_create_file(tmp_path):
    """create_file over the O_DIRECT writer: fallocate + aligned
    stream + unaligned tail, exact-size enforcement intact."""
    import io as _io
    import minio_tpu.storage.xl_storage as xs
    drive = xs.XLStorage(str(tmp_path / "d"), direct_io=True)
    drive.make_vol("v")
    payload = bytes(range(256)) * 20000 + b"tail"   # 5.12 MB + 4
    drive.create_file("v", "big", len(payload), _io.BytesIO(payload))
    assert drive.read_all("v", "big") == payload
    from minio_tpu.storage import errors as serr
    import pytest as _pytest
    with _pytest.raises(serr.LessData):
        drive.create_file("v", "short", 100, _io.BytesIO(b"x"))


def test_direct_io_full_engine_put_get(tmp_path):
    """End-to-end: an erasure engine over direct-io drives round-trips
    objects (the bitrot frame cadence is maximally unaligned)."""
    import os as _os
    import minio_tpu.storage.xl_storage as xs
    from minio_tpu.object.sets import ErasureSets
    try:
        fd = _os.open(str(tmp_path / "probe"),
                      _os.O_WRONLY | _os.O_CREAT | _os.O_DIRECT)
        _os.close(fd)
    except OSError:
        import pytest as _pytest
        _pytest.skip("filesystem lacks O_DIRECT")
    _os.environ["MINIO_TPU_DIRECT_IO"] = "on"
    try:
        sets = ErasureSets.from_drives(
            [str(tmp_path / f"d{i}") for i in range(4)], 1, 4, 2,
            block_size=1 << 16)
        sets.make_bucket("b")
        payload = _os.urandom(300_000)
        sets.put_object("b", "o", payload)
        _, stream = sets.get_object("b", "o")
        assert b"".join(stream) == payload
        sets.close()
    finally:
        _os.environ.pop("MINIO_TPU_DIRECT_IO", None)
