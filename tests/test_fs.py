"""FS backend: the full handler surface over a plain directory tree
(reference fs-v1 + ExecObjectLayerTest's FS leg)."""

from __future__ import annotations

import hashlib
import http.client
import os
import urllib.parse

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.fs import FSObjects
from minio_tpu.object.multipart import CompletePart
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("fstestkey123", "fstestsecret123")
REGION = "us-east-1"


@pytest.fixture()
def fs(tmp_path):
    return FSObjects(str(tmp_path / "fsroot"))


def test_fs_object_lifecycle(fs):
    fs.make_bucket("b")
    assert fs.bucket_exists("b")
    payload = os.urandom(3 << 20)
    info = fs.put_object("b", "dir/obj.bin", payload)
    assert info.etag == hashlib.md5(payload).hexdigest()
    assert info.size == len(payload)

    # the object is a PLAIN FILE at the expected path
    assert open(os.path.join(fs.root, "b", "dir", "obj.bin"),
                "rb").read() == payload

    got_info, stream = fs.get_object("b", "dir/obj.bin")
    assert b"".join(stream) == payload
    _, stream = fs.get_object("b", "dir/obj.bin", offset=100, length=50)
    assert b"".join(stream) == payload[100:150]

    objs, prefixes, _ = fs.list_objects("b", delimiter="/")
    assert prefixes == ["dir/"] and not objs
    objs, _, _ = fs.list_objects("b", prefix="dir/")
    assert [o.name for o in objs] == ["dir/obj.bin"]

    fs.delete_object("b", "dir/obj.bin")
    with pytest.raises(api_errors.ObjectNotFound):
        fs.get_object_info("b", "dir/obj.bin")
    # empty dirs pruned
    assert not os.path.exists(os.path.join(fs.root, "b", "dir"))
    fs.delete_bucket("b")
    assert not fs.bucket_exists("b")


def test_fs_metadata_and_update(fs):
    fs.make_bucket("m")
    fs.put_object("m", "o", b"x", opts=__import__(
        "minio_tpu.object.engine", fromlist=["PutOptions"]).PutOptions(
        metadata={"content-type": "text/css",
                  "X-Amz-Meta-Color": "blue"}))
    info = fs.get_object_info("m", "o")
    assert info.content_type == "text/css"
    assert info.user_defined["X-Amz-Meta-Color"] == "blue"
    fs.update_object_metadata("m", "o", {"content-type": "text/css",
                                         "X-Amz-Meta-Color": "red"})
    assert fs.get_object_info("m", "o").user_defined[
        "X-Amz-Meta-Color"] == "red"


def test_fs_multipart(fs):
    fs.make_bucket("mp")
    uid = fs.new_multipart_upload("mp", "big")
    p1 = os.urandom(5 << 20)
    p2 = os.urandom(1 << 20)
    i1 = fs.put_object_part("mp", "big", uid, 1, p1)
    i2 = fs.put_object_part("mp", "big", uid, 2, p2)
    parts = fs.list_object_parts("mp", "big", uid)
    assert [p.number for p in parts] == [1, 2]
    ups = fs.list_multipart_uploads("mp")
    assert ups and ups[0]["upload_id"] == uid
    info = fs.complete_multipart_upload(
        "mp", "big", uid,
        [CompletePart(1, i1.etag), CompletePart(2, i2.etag)])
    assert info.etag.endswith("-2")
    _, stream = fs.get_object("mp", "big")
    assert b"".join(stream) == p1 + p2
    assert fs.list_multipart_uploads("mp") == []


def test_fs_over_http(tmp_path):
    fs = FSObjects(str(tmp_path / "httproot"))
    srv = S3Server(fs, creds=CREDS, region=REGION).start()
    try:
        def req(method, path, body=b"", query=None, headers=None):
            query = {k: [v] for k, v in (query or {}).items()}
            qs = urllib.parse.urlencode(
                {k: v[0] for k, v in query.items()})
            hdrs = {k.lower(): v for k, v in (headers or {}).items()}
            hdrs["host"] = f"127.0.0.1:{srv.port}"
            hdrs = sig.sign_v4(method, urllib.parse.quote(path), query,
                               hdrs, hashlib.sha256(body).hexdigest(),
                               CREDS, REGION)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request(method, urllib.parse.quote(path) +
                         (f"?{qs}" if qs else ""), body=body,
                         headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
            return resp.status, data

        assert req("PUT", "/web")[0] == 200
        payload = b"fs over http" * 1000
        assert req("PUT", "/web/a/b.txt", body=payload)[0] == 200
        st, got = req("GET", "/web/a/b.txt")
        assert st == 200 and got == payload
        st, body = req("GET", "/web", query={"list-type": "2"})
        assert st == 200 and b"a/b.txt" in body
        assert req("DELETE", "/web/a/b.txt")[0] == 204
        assert req("GET", "/web/a/b.txt")[0] == 404
    finally:
        srv.stop()


def test_fs_node_boot(tmp_path):
    from minio_tpu.cluster import start_fs
    node = start_fs(str(tmp_path / "fsnode"), port=0, creds=CREDS)
    try:
        node.object_layer.make_bucket("boot")
        node.object_layer.put_object("boot", "k", b"v")
        # IAM persists through the FS layer too
        node.iam.add_user("fsuser", "fsusersecret1")
        node.iam.attach_policy("readonly", user="fsuser")
        assert node.iam.get_credentials("fsuser") is not None
    finally:
        node.shutdown()

def test_tls_server(tmp_path):
    """HTTPS listener: self-signed cert, full request over TLS
    (reference pkg/certs hot-reload is ops detail; the TLS serving path
    is what the weak-list flagged)."""
    import datetime
    import ssl
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.IPAddress(__import__("ipaddress").ip_address(
                    "127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    certfile = tmp_path / "tls.crt"
    keyfile = tmp_path / "tls.key"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))

    fs = FSObjects(str(tmp_path / "tlsroot"))
    srv = S3Server(fs, creds=CREDS, certfile=str(certfile),
                   keyfile=str(keyfile)).start()
    try:
        assert srv.url.startswith("https://")
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        conn = http.client.HTTPSConnection("127.0.0.1", srv.port,
                                           context=ctx, timeout=10)
        body = b""
        hdrs = {"host": f"127.0.0.1:{srv.port}"}
        hdrs = sig.sign_v4("PUT", "/tlsb", {}, hdrs,
                           hashlib.sha256(body).hexdigest(), CREDS,
                           REGION)
        conn.request("PUT", "/tlsb", body=body, headers=hdrs)
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        conn.close()
        assert fs.bucket_exists("tlsb")
    finally:
        srv.stop()


def test_cors_preflight_and_headers(tmp_path):
    fs = FSObjects(str(tmp_path / "cors"))
    srv = S3Server(fs, creds=CREDS).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("OPTIONS", "/anyb/anyk", headers={
            "Origin": "https://app.example.com",
            "Access-Control-Request-Method": "PUT"})
        r = conn.getresponse()
        r.read()
        h = {k.lower(): v for k, v in r.getheaders()}
        assert r.status == 200
        assert h["access-control-allow-origin"] == \
            "https://app.example.com"
        assert "PUT" in h["access-control-allow-methods"]

        # normal responses reflect the origin too
        body = b""
        hdrs = {"host": f"127.0.0.1:{srv.port}",
                "origin": "https://app.example.com"}
        hdrs = sig.sign_v4("PUT", "/corsb", {}, hdrs,
                           hashlib.sha256(body).hexdigest(), CREDS,
                           REGION)
        conn.request("PUT", "/corsb", body=body, headers=hdrs)
        r = conn.getresponse()
        r.read()
        h = {k.lower(): v for k, v in r.getheaders()}
        assert r.status == 200
        assert h.get("access-control-allow-origin") == \
            "https://app.example.com"
        conn.close()
    finally:
        srv.stop()


def test_fs_versions_prefix_only_pages_resume(fs):
    """Delimiter versions paging where whole pages are CommonPrefixes:
    the resume marker must be the rolled-up prefix (an empty or
    object-derived marker would refetch the same page forever)."""
    fs.make_bucket("b")
    for i in range(5):
        fs.put_object("b", f"dir{i}/x", b"v")
    fs.put_object("b", "zzz", b"v")
    seen_prefixes, seen_objs = [], []
    marker, vmarker = "", ""
    for _ in range(10):
        vers, pfx, nkm, nvm, trunc = fs.list_object_versions(
            "b", marker=marker, version_marker=vmarker,
            max_keys=2, delimiter="/")
        seen_prefixes += pfx
        seen_objs += [v.name for v in vers]
        if not trunc:
            break
        assert nkm, "truncated page must carry a resume marker"
        marker, vmarker = nkm, nvm
    else:
        pytest.fail("versions paging did not terminate")
    assert seen_prefixes == [f"dir{i}/" for i in range(5)]
    assert seen_objs == ["zzz"]
