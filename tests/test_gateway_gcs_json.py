"""GCS gateway, JSON API mode (VERDICT r4 #6): an in-process GCS fake
speaking the storage/v1 JSON API (+ OAuth token endpoint) exercises
object CRUD, listing, error mapping, and the compose-based multipart —
matching cmd/gateway/gcs/gateway-gcs.go behavior."""

from __future__ import annotations

import base64
import hashlib
import http.server
import json
import re
import threading
import urllib.parse

import pytest

from minio_tpu.gateway import new_gateway
from minio_tpu.gateway import gcs as gcs_mod
from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions
from minio_tpu.object.multipart import CompletePart


class FakeGCS(http.server.BaseHTTPRequestHandler):
    """storage/v1 JSON API subset + OAuth2 token endpoint."""

    buckets: dict = {}          # name -> {objects: {name: obj}}
    tokens_issued: int = 0
    compose_calls: list = []
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    # -- helpers -----------------------------------------------------------

    def _json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _err(self, status: int, reason: str, msg: str = "") -> None:
        self._json(status, {"error": {
            "code": status, "message": msg or reason,
            "errors": [{"reason": reason, "message": msg or reason}]}})

    def _authed(self) -> bool:
        auth = self.headers.get("Authorization", "")
        if auth != "Bearer fake-gcs-token":
            self._err(401, "authError", "bad token")
            return False
        return True

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(n)

    @staticmethod
    def _obj_json(name: str, obj: dict) -> dict:
        out = {"name": name, "bucket": obj["bucket"],
               "size": str(len(obj["data"])),
               "etag": obj["etag"],
               "contentType": obj.get("contentType", ""),
               "metadata": obj.get("metadata", {}),
               "updated": "2026-07-30T12:00:00Z",
               "timeCreated": "2026-07-30T12:00:00Z"}
        if obj.get("md5") is not None:
            out["md5Hash"] = base64.b64encode(obj["md5"]).decode()
        return out

    def _route(self):
        u = urllib.parse.urlsplit(self.path)
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(u.query).items()}
        return u.path, q

    # -- verbs -------------------------------------------------------------

    def do_POST(self):
        path, q = self._route()
        if path == "/token":
            body = urllib.parse.parse_qs(self._body().decode())
            assertion = body.get("assertion", [""])[0]
            grant = body.get("grant_type", [""])[0]
            if grant != "urn:ietf:params:oauth:grant-type:jwt-bearer" \
                    or assertion.count(".") != 2:
                return self._err(400, "invalid_grant")
            # validate the JWT claims are well-formed (unverified)
            claims = json.loads(base64.urlsafe_b64decode(
                assertion.split(".")[1] + "=="))
            if not claims.get("iss") or not claims.get("scope"):
                return self._err(400, "invalid_grant")
            type(self).tokens_issued += 1
            return self._json(200, {"access_token": "fake-gcs-token",
                                    "expires_in": 3600})
        if not self._authed():
            return
        if path == "/storage/v1/b":
            name = json.loads(self._body()).get("name", "")
            if name in self.buckets:
                return self._err(409, "conflict", "bucket exists")
            self.buckets[name] = {}
            return self._json(200, {"name": name,
                                    "timeCreated":
                                        "2026-07-30T12:00:00Z"})
        m = re.match(r"^/upload/storage/v1/b/([^/]+)/o$", path)
        if m and q.get("uploadType") == "multipart":
            bucket = urllib.parse.unquote(m.group(1))
            if bucket not in self.buckets:
                return self._err(404, "notFound", "no bucket")
            ctype = self.headers.get("Content-Type", "")
            bm = re.search(r'boundary=([^\s;]+)', ctype)
            parts = self._body().split(
                b"--" + bm.group(1).encode())
            # parts[1] = json meta, parts[2] = media
            def _payload(raw: bytes) -> bytes:
                return raw.split(b"\r\n\r\n", 1)[1].rsplit(
                    b"\r\n", 1)[0]
            meta = json.loads(_payload(parts[1]))
            data = _payload(parts[2])
            mt = re.search(rb"Content-Type:\s*([^\r\n]+)", parts[2])
            obj = {"bucket": bucket, "data": data,
                   "md5": hashlib.md5(data).digest(),
                   "etag": f"W/\"{hashlib.md5(data).hexdigest()}\"",
                   "contentType": meta.get(
                       "contentType",
                       mt.group(1).decode() if mt else ""),
                   "metadata": meta.get("metadata", {})}
            self.buckets[bucket][meta["name"]] = obj
            return self._json(200, self._obj_json(meta["name"], obj))
        m = re.match(r"^/storage/v1/b/([^/]+)/o/(.+)/compose$", path)
        if m:
            bucket = urllib.parse.unquote(m.group(1))
            dst = urllib.parse.unquote(m.group(2))
            if bucket not in self.buckets:
                return self._err(404, "notFound", "no bucket")
            req = json.loads(self._body())
            sources = [s["name"] for s in req.get("sourceObjects", [])]
            if len(sources) > 32:
                return self._err(400, "invalid",
                                 "too many compose components")
            type(self).compose_calls.append((dst, list(sources)))
            data = b""
            for s in sources:
                src = self.buckets[bucket].get(s)
                if src is None:
                    return self._err(404, "notFound", f"missing {s}")
                data += src["data"]
            dest_meta = req.get("destination", {})
            obj = {"bucket": bucket, "data": data, "md5": None,
                   "etag": f"W/\"composite-{len(data)}\"",
                   "contentType": dest_meta.get("contentType", ""),
                   "metadata": dest_meta.get("metadata", {})}
            self.buckets[bucket][dst] = obj
            return self._json(200, self._obj_json(dst, obj))
        return self._err(404, "notFound", path)

    def do_GET(self):
        if not self._authed():
            return
        path, q = self._route()
        if path == "/storage/v1/b":
            return self._json(200, {"items": [
                {"name": b, "timeCreated": "2026-07-30T12:00:00Z"}
                for b in sorted(self.buckets)]})
        m = re.match(r"^/storage/v1/b/([^/]+)$", path)
        if m:
            b = urllib.parse.unquote(m.group(1))
            if b not in self.buckets:
                return self._err(404, "notFound", "no bucket")
            return self._json(200, {
                "name": b, "timeCreated": "2026-07-30T12:00:00Z"})
        m = re.match(r"^/storage/v1/b/([^/]+)/o$", path)
        if m:
            return self._list(urllib.parse.unquote(m.group(1)), q)
        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$", path)
        if m:
            bucket = urllib.parse.unquote(m.group(1))
            name = urllib.parse.unquote(m.group(2))
            obj = self.buckets.get(bucket, {}).get(name)
            if obj is None:
                return self._err(404, "notFound", "no object")
            if q.get("alt") == "media":
                data = obj["data"]
                status = 200
                rng = self.headers.get("Range", "")
                rm = re.match(r"bytes=(\d+)-(\d*)$", rng)
                if rm:
                    lo = int(rm.group(1))
                    hi = int(rm.group(2)) if rm.group(2) else \
                        len(data) - 1
                    data = data[lo:hi + 1]
                    status = 206
                self.send_response(status)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            return self._json(200, self._obj_json(name, obj))
        return self._err(404, "notFound", path)

    def _list(self, bucket: str, q: dict) -> None:
        if bucket not in self.buckets:
            return self._err(404, "notFound", "no bucket")
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        start = q.get("startOffset", "")
        maxr = int(q.get("maxResults", 1000))
        token = int(q.get("pageToken", 0) or 0)
        names = sorted(n for n in self.buckets[bucket]
                       if n.startswith(prefix) and n >= start)
        items, prefixes = [], []
        for n in names:
            if delim:
                rest = n[len(prefix):]
                if delim in rest:
                    p = prefix + rest.split(delim, 1)[0] + delim
                    if p not in prefixes:
                        prefixes.append(p)
                    continue
            items.append(n)
        page = items[token:token + maxr]
        out = {"items": [self._obj_json(n, self.buckets[bucket][n])
                         for n in page],
               "prefixes": prefixes}
        if token + maxr < len(items):
            out["nextPageToken"] = str(token + maxr)
        self._json(200, out)

    def do_DELETE(self):
        if not self._authed():
            return
        path, _q = self._route()
        m = re.match(r"^/storage/v1/b/([^/]+)$", path)
        if m:
            b = urllib.parse.unquote(m.group(1))
            if b not in self.buckets:
                return self._err(404, "notFound", "no bucket")
            if self.buckets[b]:
                return self._err(409, "conflict",
                                 "The bucket you tried to delete is "
                                 "not empty.")
            del self.buckets[b]
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$", path)
        if m:
            bucket = urllib.parse.unquote(m.group(1))
            name = urllib.parse.unquote(m.group(2))
            if self.buckets.get(bucket, {}).pop(name, None) is None:
                return self._err(404, "notFound", "no object")
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        return self._err(404, "notFound", path)

    def do_PATCH(self):
        if not self._authed():
            return
        path, _q = self._route()
        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$", path)
        if not m:
            return self._err(404, "notFound", path)
        bucket = urllib.parse.unquote(m.group(1))
        name = urllib.parse.unquote(m.group(2))
        obj = self.buckets.get(bucket, {}).get(name)
        if obj is None:
            return self._err(404, "notFound", "no object")
        obj["metadata"] = json.loads(self._body()).get("metadata", {})
        self._json(200, self._obj_json(name, obj))


@pytest.fixture()
def gcs_fake():
    FakeGCS.buckets = {}
    FakeGCS.tokens_issued = 0
    FakeGCS.compose_calls = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeGCS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def _rsa_sa_json(port: int) -> str:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537,
                                   key_size=2048)
    pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()).decode()
    return json.dumps({
        "type": "service_account",
        "project_id": "test-project",
        "client_email": "svc@test-project.iam.gserviceaccount.com",
        "private_key": pem,
        "token_uri": f"http://127.0.0.1:{port}/token"})


@pytest.fixture()
def gw(gcs_fake):
    layer = new_gateway("gcs", credentials_json=_rsa_sa_json(gcs_fake),
                        host="127.0.0.1", port=gcs_fake, secure=False)
    assert isinstance(layer, gcs_mod.GCSJsonGatewayObjects)
    return layer


def test_oauth_jwt_grant_flow(gw):
    """The service-account JWT-bearer grant runs against the token
    endpoint once and the token is reused."""
    gw.make_bucket("authb")
    gw.list_buckets()
    gw.bucket_exists("authb")
    assert FakeGCS.tokens_issued == 1
    assert gw.storage_info()["backend"] == "gateway-gcs"


def test_bucket_and_object_crud(gw):
    gw.make_bucket("jb")
    with pytest.raises(api_errors.BucketExists):
        gw.make_bucket("jb")
    assert [v.name for v in gw.list_buckets()] == ["jb"]
    with pytest.raises(api_errors.BucketNotFound):
        gw.get_bucket_info("ghost")

    payload = b"json-api object body " * 100
    info = gw.put_object(
        "jb", "dir/obj.bin", payload,
        opts=PutOptions(metadata={"content-type": "application/x-t",
                                  "x-amz-meta-k": "v"}))
    assert info.etag == hashlib.md5(payload).hexdigest()
    assert info.size == len(payload)

    got = gw.get_object_info("jb", "dir/obj.bin")
    assert got.size == len(payload)
    assert got.content_type == "application/x-t"
    assert got.user_defined.get("x-amz-meta-k") == "v"

    _, stream = gw.get_object("jb", "dir/obj.bin")
    assert b"".join(stream) == payload
    _, stream = gw.get_object("jb", "dir/obj.bin", offset=10,
                              length=50)
    assert b"".join(stream) == payload[10:60]

    gw.update_object_metadata("jb", "dir/obj.bin",
                              {"x-amz-meta-k": "v2"})
    assert gw.get_object_info(
        "jb", "dir/obj.bin").user_defined["x-amz-meta-k"] == "v2"

    gw.delete_object("jb", "dir/obj.bin")
    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("jb", "dir/obj.bin")
    with pytest.raises(api_errors.ObjectNotFound):
        gw.delete_object("jb", "dir/obj.bin")


def test_delete_nonempty_bucket_maps_to_bucket_not_empty(gw):
    gw.make_bucket("full")
    gw.put_object("full", "keep", b"x")
    with pytest.raises(api_errors.BucketNotEmpty):
        gw.delete_bucket("full")
    gw.delete_object("full", "keep")
    gw.delete_bucket("full")
    assert not gw.bucket_exists("full")


def test_bucket_exists_propagates_auth_failures(gw):
    """A revoked token must surface as an error, never as 'the bucket
    does not exist' (which tricks callers into re-creating it)."""
    gw.make_bucket("realb")
    gw.c._token = "revoked"
    gw.c._token_exp = __import__("time").time() + 3600
    try:
        with pytest.raises(api_errors.ObjectApiError):
            gw.bucket_exists("realb")
    finally:
        gw.c._token = ""
        gw.c._token_exp = 0.0
    assert gw.bucket_exists("realb")


def test_listing_delimiter_marker_and_sys_tmp_hidden(gw):
    gw.make_bucket("lb")
    for name in ("a.txt", "b/one", "b/two", "c.txt",
                 "minio.sys.tmp/multipart/v1/u1/gcs.json"):
        gw.put_object("lb", name, b"x")
    objs, prefixes, _ = gw.list_objects("lb", delimiter="/")
    assert [o.name for o in objs] == ["a.txt", "c.txt"]
    assert prefixes == ["b/"]               # staging area hidden
    objs, _, _ = gw.list_objects("lb", prefix="b/")
    assert [o.name for o in objs] == ["b/one", "b/two"]
    objs, _, _ = gw.list_objects("lb", marker="b/one")
    assert [o.name for o in objs] == ["b/two", "c.txt"]


def test_multipart_compose_roundtrip(gw, monkeypatch):
    monkeypatch.setattr(gcs_mod, "MIN_PART_SIZE", 1)
    gw.make_bucket("mb")
    uid = gw.new_multipart_upload(
        "mb", "big.bin",
        PutOptions(metadata={"content-type": "application/x-big",
                             "x-amz-meta-tag": "mpu"}))
    # the session meta object exists in the reference's staging path
    assert gw.c.get_object_meta(
        "mb", f"minio.sys.tmp/multipart/v1/{uid}/gcs.json")

    chunks = [b"A" * 1000, b"B" * 2000, b"C" * 300]
    parts = []
    for i, chunk in enumerate(chunks, start=1):
        p = gw.put_object_part("mb", "big.bin", uid, i, chunk)
        parts.append(CompletePart(i, p.etag))
    listed = gw.list_object_parts("mb", "big.bin", uid)
    assert [p.number for p in listed] == [1, 2, 3]
    assert [u["upload_id"] for u in
            gw.list_multipart_uploads("mb")] == [uid]

    info = gw.complete_multipart_upload("mb", "big.bin", uid, parts)
    md5s = b"".join(bytes.fromhex(cp.etag) for cp in parts)
    assert info.etag == f"{hashlib.md5(md5s).hexdigest()}-3"
    _, stream = gw.get_object("mb", "big.bin")
    assert b"".join(stream) == b"".join(chunks)
    got = gw.get_object_info("mb", "big.bin")
    assert got.content_type == "application/x-big"
    assert got.user_defined.get("x-amz-meta-tag") == "mpu"
    # staging fully cleaned up
    assert FakeGCS.buckets["mb"].keys() == {"big.bin"}

    with pytest.raises(api_errors.InvalidUploadID):
        gw.put_object_part("mb", "big.bin", uid, 4, b"late")


def test_multipart_over_32_parts_composes_in_groups(gw, monkeypatch):
    """33+ parts exceed the GCS compose limit: groups of <= 32 compose
    into intermediates, then the intermediates compose into the final
    object (gateway-gcs.go:1339)."""
    monkeypatch.setattr(gcs_mod, "MIN_PART_SIZE", 1)
    gw.make_bucket("gb")
    uid = gw.new_multipart_upload("gb", "huge.bin", PutOptions())
    parts = []
    want = b""
    for i in range(1, 34):
        chunk = bytes([i]) * 10
        want += chunk
        p = gw.put_object_part("gb", "huge.bin", uid, i, chunk)
        parts.append(CompletePart(i, p.etag))
    FakeGCS.compose_calls = []
    gw.complete_multipart_upload("gb", "huge.bin", uid, parts)
    # every compose respected the 32-source limit; the final compose
    # consumed the two intermediates
    assert all(len(srcs) <= 32 for _, srcs in FakeGCS.compose_calls)
    dsts = [d for d, _ in FakeGCS.compose_calls]
    assert dsts[-1] == "huge.bin"
    assert len(FakeGCS.compose_calls) == 3      # 32 + 1, then final
    assert len(FakeGCS.compose_calls[-1][1]) == 2
    _, stream = gw.get_object("gb", "huge.bin")
    assert b"".join(stream) == want
    assert FakeGCS.buckets["gb"].keys() == {"huge.bin"}


def test_multipart_part_too_small_and_abort(gw, monkeypatch):
    gw.make_bucket("sb")
    uid = gw.new_multipart_upload("sb", "o", PutOptions())
    p1 = gw.put_object_part("sb", "o", uid, 1, b"tiny")
    p2 = gw.put_object_part("sb", "o", uid, 2, b"tail")
    with pytest.raises(api_errors.PartTooSmall):
        gw.complete_multipart_upload(
            "sb", "o", uid,
            [CompletePart(1, p1.etag), CompletePart(2, p2.etag)])
    # bad part etag -> InvalidPart
    monkeypatch.setattr(gcs_mod, "MIN_PART_SIZE", 1)
    with pytest.raises(api_errors.InvalidPart):
        gw.complete_multipart_upload(
            "sb", "o", uid,
            [CompletePart(1, "0" * 32), CompletePart(2, p2.etag)])
    gw.abort_multipart_upload("sb", "o", uid)
    assert FakeGCS.buckets["sb"] == {}
    with pytest.raises(api_errors.InvalidUploadID):
        gw.abort_multipart_upload("sb", "o", uid)
