"""SSE-S3 / SSE-C + inline compression over the live S3 server:
ETag-of-plaintext semantics, ranged reads over packages, key
enforcement, copy behavior (reference cmd/encryption-v1.go and
compression test intents)."""

from __future__ import annotations

import base64
import hashlib
import http.client
import os
import urllib.parse

import pytest

from minio_tpu.features import crypto as sse
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("ssetestkey1", "ssetestsecret1")
REGION = "us-east-1"
MASTER = hashlib.sha256(b"test-master-key").digest()


class Client:
    def __init__(self, port, creds=CREDS):
        self.port, self.creds = port, creds

    def request(self, method, path, query=None, body=b"", headers=None):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"127.0.0.1:{self.port}"
        payload_hash = hashlib.sha256(body).hexdigest()
        hdrs = sig.sign_v4(method, urllib.parse.quote(path), query, hdrs,
                           payload_hash, self.creds, REGION)
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=30)
        conn.request(method, urllib.parse.quote(path) +
                     (f"?{qs}" if qs else ""), body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        out = {k.lower(): v for k, v in resp.getheaders()}
        conn.close()
        return resp.status, out, data


def ssec_headers(key: bytes) -> dict:
    return {
        "x-amz-server-side-encryption-customer-algorithm": "AES256",
        "x-amz-server-side-encryption-customer-key":
            base64.b64encode(key).decode(),
        "x-amz-server-side-encryption-customer-key-md5":
            base64.b64encode(hashlib.md5(key).digest()).decode(),
    }


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("ssedrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 17)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    from minio_tpu.features.kms import StaticKMS
    srv.api.kms = StaticKMS(MASTER)
    srv.api.compression_enabled = True
    yield srv
    srv.stop()
    sets.close()


@pytest.fixture(scope="module")
def client(server):
    c = Client(server.port)
    assert c.request("PUT", "/sseb")[0] == 200
    return c


# ---------------------------------------------------------------------------
# unit: transforms
# ---------------------------------------------------------------------------

def test_encrypt_decrypt_roundtrip_sizes():
    oek, nonce = os.urandom(32), os.urandom(12)
    for n in (0, 1, 100, sse.PKG_SIZE, sse.PKG_SIZE + 1,
              3 * sse.PKG_SIZE + 777):
        pt = os.urandom(n)
        enc = sse.Encryptor(oek, nonce)
        ct = enc.update(pt) + enc.finalize()
        assert len(ct) == sse.encrypted_size(n)
        got = b"".join(sse.decrypt_stream(iter([ct]), oek, nonce))
        assert got == pt


def test_decrypt_from_middle_package():
    oek, nonce = os.urandom(32), os.urandom(12)
    pt = os.urandom(3 * sse.PKG_SIZE + 100)
    enc = sse.Encryptor(oek, nonce)
    ct = enc.update(pt) + enc.finalize()
    pkg = sse.PKG_SIZE + sse.TAG_SIZE
    got = b"".join(sse.decrypt_stream(iter([ct[pkg:]]), oek, nonce,
                                      start_seq=1))
    assert got == pt[sse.PKG_SIZE:]


def test_seal_unseal_wrong_key():
    oek = os.urandom(32)
    sealed = sse.seal_key(MASTER, oek)
    assert sse.unseal_key(MASTER, sealed) == oek
    with pytest.raises(Exception):
        sse.unseal_key(os.urandom(32), sealed)


# ---------------------------------------------------------------------------
# e2e: SSE-S3
# ---------------------------------------------------------------------------

def test_sse_s3_roundtrip_and_etag(client):
    payload = os.urandom(200_000)
    st, h, _ = client.request(
        "PUT", "/sseb/s3enc.dat", body=payload,
        headers={"x-amz-server-side-encryption": "AES256"})
    assert st == 200
    assert h.get("x-amz-server-side-encryption") == "AES256"
    # ETag is the MD5 of the PLAINTEXT
    assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()

    st, h, got = client.request("GET", "/sseb/s3enc.dat")
    assert st == 200 and got == payload
    assert h.get("x-amz-server-side-encryption") == "AES256"
    assert int(h["content-length"]) == len(payload)

    # HEAD shows plaintext size
    st, h, _ = client.request("HEAD", "/sseb/s3enc.dat")
    assert st == 200 and int(h["content-length"]) == len(payload)

    # internal seals never leak
    assert not any(k.lower().startswith("x-minio-internal") for k in h)


def test_sse_s3_ranged_get(client):
    payload = os.urandom(3 * sse.PKG_SIZE + 500)
    client.request("PUT", "/sseb/ranged.dat", body=payload,
                   headers={"x-amz-server-side-encryption": "AES256"})
    for start, end in ((0, 99), (sse.PKG_SIZE - 10, sse.PKG_SIZE + 10),
                      (2 * sse.PKG_SIZE + 7, 3 * sse.PKG_SIZE + 499),
                      (len(payload) - 100, len(payload) - 1)):
        st, h, got = client.request(
            "GET", "/sseb/ranged.dat",
            headers={"range": f"bytes={start}-{end}"})
        assert st == 206
        assert got == payload[start:end + 1], (start, end)
        assert h["content-range"].endswith(f"/{len(payload)}")


# ---------------------------------------------------------------------------
# e2e: SSE-C
# ---------------------------------------------------------------------------

def test_sse_c_requires_key(client):
    key = os.urandom(32)
    payload = b"customer secret data" * 1000
    st, h, _ = client.request("PUT", "/sseb/cenc.dat", body=payload,
                              headers=ssec_headers(key))
    assert st == 200
    assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()

    # without the key: denied
    st, _, _ = client.request("GET", "/sseb/cenc.dat")
    assert st == 403
    # wrong key: denied
    st, _, _ = client.request("GET", "/sseb/cenc.dat",
                              headers=ssec_headers(os.urandom(32)))
    assert st == 403
    # right key: plaintext
    st, h, got = client.request("GET", "/sseb/cenc.dat",
                                headers=ssec_headers(key))
    assert st == 200 and got == payload
    assert h.get(
        "x-amz-server-side-encryption-customer-algorithm") == "AES256"

    # HEAD without key is denied too
    assert client.request("HEAD", "/sseb/cenc.dat")[0] == 403


# ---------------------------------------------------------------------------
# e2e: compression
# ---------------------------------------------------------------------------

def test_compression_roundtrip_and_actual_size(server, client):
    payload = b"A compressible line of text.\n" * 10_000
    st, h, _ = client.request("PUT", "/sseb/big.log", body=payload)
    assert st == 200
    assert h["etag"].strip('"') == hashlib.md5(payload).hexdigest()

    st, h, got = client.request("GET", "/sseb/big.log")
    assert st == 200 and got == payload
    assert int(h["content-length"]) == len(payload)

    # stored bytes really are compressed (smaller than the payload)
    info = server.api.obj.get_object_info("sseb", "big.log")
    assert info.size < len(payload) // 2

    # ranged read over compressed data
    st, _, got = client.request("GET", "/sseb/big.log",
                                headers={"range": "bytes=1000-2999"})
    assert st == 206 and got == payload[1000:3000]


def test_compressed_and_encrypted(client):
    payload = b"text " * 50_000
    key = os.urandom(32)
    st, _, _ = client.request("PUT", "/sseb/both.txt", body=payload,
                              headers=ssec_headers(key))
    assert st == 200
    st, _, got = client.request("GET", "/sseb/both.txt",
                                headers=ssec_headers(key))
    assert st == 200 and got == payload


def test_copy_preserves_encryption(client):
    payload = os.urandom(50_000)
    client.request("PUT", "/sseb/src.dat", body=payload,
                   headers={"x-amz-server-side-encryption": "AES256"})
    st, h, _ = client.request(
        "PUT", "/sseb/dst.dat",
        headers={"x-amz-copy-source": "/sseb/src.dat",
                 "x-amz-metadata-directive": "REPLACE",
                 "content-type": "application/x-new"})
    assert st == 200
    st, h, got = client.request("GET", "/sseb/dst.dat")
    assert st == 200 and got == payload

def _multipart_sse(client, key_headers, bucket_key, parts_payloads):
    st, _, body = client.request("POST", bucket_key, query={"uploads": ""},
                                 headers=dict(key_headers))
    assert st == 200, body
    import xml.etree.ElementTree as ET
    ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
    root = ET.fromstring(body)
    uid = (root.find("s3:UploadId", ns) if root.find("s3:UploadId", ns)
           is not None else root.find("UploadId")).text
    etags = []
    for i, payload in enumerate(parts_payloads, start=1):
        st, h, body = client.request(
            "PUT", bucket_key, query={"uploadId": uid,
                                      "partNumber": str(i)},
            body=payload, headers=dict(key_headers))
        assert st == 200, body
        etags.append(h["etag"].strip('"'))
        # part ETag is the PLAINTEXT md5
        assert etags[-1] == hashlib.md5(payload).hexdigest()
    complete = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
        for i, e in enumerate(etags, start=1)) + \
        "</CompleteMultipartUpload>"
    st, _, body = client.request("POST", bucket_key,
                                 query={"uploadId": uid},
                                 body=complete.encode())
    assert st == 200, body
    return uid


def test_multipart_sse_s3_roundtrip(client):
    p1 = os.urandom(5 << 20)                       # 5 MiB min part
    p2 = os.urandom(sse.PKG_SIZE + 12345)
    _multipart_sse(client, {"x-amz-server-side-encryption": "AES256"},
                   "/sseb/mp-s3.bin", [p1, p2])
    want = p1 + p2
    st, h, got = client.request("GET", "/sseb/mp-s3.bin")
    assert st == 200 and got == want
    assert int(h["content-length"]) == len(want)
    assert h.get("x-amz-server-side-encryption") == "AES256"
    # HEAD shows the plaintext size
    st, h, _ = client.request("HEAD", "/sseb/mp-s3.bin")
    assert int(h["content-length"]) == len(want)
    # ranged reads across the part boundary
    for start, end in ((0, 99), (len(p1) - 50, len(p1) + 50),
                      (len(want) - 100, len(want) - 1)):
        st, _, got = client.request(
            "GET", "/sseb/mp-s3.bin",
            headers={"range": f"bytes={start}-{end}"})
        assert st == 206 and got == want[start:end + 1], (start, end)


def test_multipart_sse_c_requires_key_per_part(client):
    key = os.urandom(32)
    p1 = os.urandom(5 << 20)
    p2 = os.urandom(100_000)
    _multipart_sse(client, ssec_headers(key), "/sseb/mp-c.bin", [p1, p2])
    # GET without key denied; with key returns the plaintext
    assert client.request("GET", "/sseb/mp-c.bin")[0] == 403
    st, _, got = client.request("GET", "/sseb/mp-c.bin",
                                headers=ssec_headers(key))
    assert st == 200 and got == p1 + p2

    # a part upload without the key is rejected
    st, _, body = client.request("POST", "/sseb/mp-c2.bin",
                                 query={"uploads": ""},
                                 headers=ssec_headers(key))
    assert st == 200
    import xml.etree.ElementTree as ET
    uid = [e.text for e in ET.fromstring(body).iter()
           if e.tag.endswith("UploadId")][0]
    st, _, _ = client.request("PUT", "/sseb/mp-c2.bin",
                              query={"uploadId": uid, "partNumber": "1"},
                              body=b"x" * 1000)
    assert st == 403


def test_sse_kms_algo_rejected(client):
    st, _, body = client.request(
        "PUT", "/sseb/kms.bin", body=b"x",
        headers={"x-amz-server-side-encryption": "aws:kms"})
    assert st == 501
    st, _, _ = client.request(
        "POST", "/sseb/kmsmp.bin", query={"uploads": ""},
        headers={"x-amz-server-side-encryption": "aws:kms"})
    assert st == 501


def test_multipart_sse_on_fs_backend(tmp_path):
    """The FS backend records part boundaries, so multipart SSE decrypts
    there too (single-drive deployments)."""
    from minio_tpu.object.fs import FSObjects
    fs = FSObjects(str(tmp_path / "fsmp"))
    srv = S3Server(fs, creds=CREDS, region=REGION).start()
    from minio_tpu.features.kms import StaticKMS
    srv.api.kms = StaticKMS(MASTER)
    try:
        c = Client(srv.port)
        assert c.request("PUT", "/fsb")[0] == 200
        p1 = os.urandom(5 << 20)
        p2 = os.urandom(70_000)
        _multipart_sse(c, {"x-amz-server-side-encryption": "AES256"},
                       "/fsb/mp.bin", [p1, p2])
        st, h, got = c.request("GET", "/fsb/mp.bin")
        assert st == 200 and got == p1 + p2
        assert int(h["content-length"]) == len(p1) + len(p2)
        st, _, got = c.request(
            "GET", "/fsb/mp.bin",
            headers={"range": f"bytes={len(p1) - 10}-{len(p1) + 9}"})
        assert st == 206 and got == (p1 + p2)[len(p1) - 10:len(p1) + 10]
    finally:
        srv.stop()


def ssec_copy_source_headers(key: bytes) -> dict:
    return {("x-amz-copy-source-server-side-encryption-customer-"
             + k.split("customer-")[1]): v
            for k, v in ssec_headers(key).items()}


def test_copy_rotates_ssec_key(client):
    """SSE-C key rotation via CopyObject (copy-source key + new key)."""
    old, new = os.urandom(32), os.urandom(32)
    payload = os.urandom(120_000)
    assert client.request("PUT", "/sseb/rot.bin", body=payload,
                          headers=ssec_headers(old))[0] == 200
    hdrs = {"x-amz-copy-source": "/sseb/rot.bin",
            "x-amz-metadata-directive": "REPLACE"}
    hdrs.update(ssec_copy_source_headers(old))
    hdrs.update(ssec_headers(new))
    st, h, body = client.request("PUT", "/sseb/rot.bin", headers=hdrs)
    assert st == 200, body
    # old key no longer opens it; new key does; bytes identical
    assert client.request("GET", "/sseb/rot.bin",
                          headers=ssec_headers(old))[0] == 403
    st, _, got = client.request("GET", "/sseb/rot.bin",
                                headers=ssec_headers(new))
    assert st == 200 and got == payload


def test_copy_encrypts_and_decrypts(client):
    payload = os.urandom(90_000)
    assert client.request("PUT", "/sseb/plainsrc.bin",
                          body=payload)[0] == 200
    # encrypt-on-copy (plain -> SSE-S3)
    st, _, _ = client.request(
        "PUT", "/sseb/enccopy.bin",
        headers={"x-amz-copy-source": "/sseb/plainsrc.bin",
                 "x-amz-server-side-encryption": "AES256"})
    assert st == 200
    st, h, got = client.request("GET", "/sseb/enccopy.bin")
    assert st == 200 and got == payload
    assert h.get("x-amz-server-side-encryption") == "AES256"

    # decrypt-on-copy (SSE-C -> plaintext, via copy-source key only)
    key = os.urandom(32)
    assert client.request("PUT", "/sseb/csrc.bin", body=payload,
                          headers=ssec_headers(key))[0] == 200
    hdrs = {"x-amz-copy-source": "/sseb/csrc.bin",
            "x-amz-metadata-directive": "REPLACE"}
    hdrs.update(ssec_copy_source_headers(key))
    st, _, _ = client.request("PUT", "/sseb/plain2.bin", headers=hdrs)
    assert st == 200
    st, h, got = client.request("GET", "/sseb/plain2.bin")
    assert st == 200 and got == payload
    assert "x-amz-server-side-encryption" not in h
