"""Event journal (ISSUE 18, tentpole layer 1) + bench_trend satellite.

Fast (tier-1) coverage of the incident plane's foundation:

  * EVENT_MATRIX names a real emit for EVERY registered event class —
    a new class without a matrix entry fails test_matrix_covers_registry
    (the crashpoint-matrix pattern), so the registry can't grow
    untested;
  * registry validation (duplicate names, bad severities, unbounded
    attr keys are rejected at define time);
  * ring/recent filter semantics, persistence roundtrip across
    instances, torn-segment tolerance (the crash window serves the
    surviving prefix), stream backlog + (node, seq) dedup against a
    grafted peer echo;
  * tools/bench_trend.py --smoke and its regression exit code.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from minio_tpu.utils import eventlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every registered event class -> a representative attr payload using
# exactly the declared attr keys. Adding a class to the registry
# without adding it here fails test_matrix_covers_registry.
EVENT_MATRIX = {
    "drive.suspect": {"drive": "/d/0", "set": 0},
    "drive.probation": {"drive": "/d/0", "set": 0},
    "drive.reconvict": {"drive": "/d/0", "set": 0},
    "drive.readmit": {"drive": "/d/0", "set": 0},
    "mrf.enqueue": {"queued": 3},
    "mrf.drain": {"healed": 2, "failed": 0},
    "admission.shed": {"reason": "staging"},
    "health.transition": {"kind": "drive", "target": "/d/0",
                          "state": "suspect", "event": "suspect"},
    "membership.generation": {"peer": "127.0.0.1:9001",
                              "generation": 42},
    "net.partition": {"rule": "both", "peers": "a|b"},
    "net.heal": {"peers": "a|b"},
    "registry.fork": {"epoch": 7, "forks": 1},
    "crashpoint.armed": {"point": "put.meta.before_rename", "nth": 1},
    "device.decline": {"stage": "scheduler", "reason": "no-device"},
    "fsck.complete": {"findings": 1, "repaired": 1, "unrepaired": 0},
    "fsck.unrepaired": {"findings": 1},
    "rebalance.checkpoint": {"pool": 0, "objects": 10},
    "resync.checkpoint": {"target": "arn:x", "objects": 5},
    "slo.breach": {"objective": "read-availability", "window": "60s",
                   "burn": 14.2},
    "slo.clear": {"objective": "read-availability"},
    "incident.captured": {"trigger": "slo.breach",
                          "incident": "inc-1-001-slo-breach",
                          "events": 12},
    "qos.update": {"epoch": 3, "tenants": 2, "tiers": 1},
    "tenant.shed": {"tenant": "alice", "reason": "rate"},
    "notify.update": {"epoch": 2, "targets": 1},
    "notify.offline": {"target": "arn:minio:sqs::hook1:webhook"},
    "notify.redrive": {"target": "arn:minio:sqs::hook1:webhook",
                       "delivered": 3},
    "notify.drop": {"target": "arn:minio:sqs::hook1:webhook"},
}


def fresh() -> eventlog.EventJournal:
    return eventlog.EventJournal()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_matrix_covers_registry():
    """Every registered event class has a matrix emit and vice versa
    — and each matrix payload uses exactly the declared attr keys."""
    registered = set(eventlog.EVENTS)
    matrix = set(EVENT_MATRIX)
    assert registered - matrix == set(), \
        f"event classes without a matrix emit: {registered - matrix}"
    assert matrix - registered == set(), \
        f"matrix names unregistered classes: {matrix - registered}"
    for name, attrs in EVENT_MATRIX.items():
        assert set(attrs) == set(eventlog.EVENTS[name].attrs), name
    assert len(registered) >= 20


def test_every_matrix_class_emits():
    j = fresh()
    for name, attrs in sorted(EVENT_MATRIX.items()):
        e = j.emit(name, **attrs)
        assert e is not None and e["class"] == name
        assert e["sev"] in eventlog.SEVERITIES
        assert e["attrs"] == attrs
    assert j.seq == len(EVENT_MATRIX)


def test_define_rejects_bad_registrations():
    with pytest.raises(ValueError):
        eventlog.define("drive.suspect", "drive", "warn", (), "dup")
    with pytest.raises(ValueError):
        eventlog.define("x.bogus-sev", "x", "fatal", (), "bad sev")
    with pytest.raises(ValueError):
        eventlog.define("x.unbounded", "x", "info", ("bucket",),
                        "unbounded attr key")
    assert "x.bogus-sev" not in eventlog.EVENTS
    assert "x.unbounded" not in eventlog.EVENTS


def test_emit_unregistered_raises():
    j = fresh()
    with pytest.raises(ValueError):
        j.emit("no.such.class", a=1)


def test_sev_rank_orders_severities():
    ranks = [eventlog.sev_rank(s) for s in eventlog.SEVERITIES]
    assert ranks == sorted(ranks) and len(set(ranks)) == len(ranks)
    assert eventlog.sev_rank("unknown") == -1


def test_render_table_lists_every_class():
    table = eventlog.render_table()
    for name in eventlog.EVENTS:
        assert f"`{name}`" in table, name


def test_emit_once_dedupes_for_process_lifetime():
    first = eventlog.emit_once("device.decline", stage="unit-test",
                               reason="once")
    again = eventlog.emit_once("device.decline", stage="unit-test",
                               reason="once")
    other = eventlog.emit_once("device.decline", stage="unit-test",
                               reason="other")
    assert first is not None and again is None
    assert other is not None


# ---------------------------------------------------------------------------
# ring + filters
# ---------------------------------------------------------------------------

def test_recent_filters_and_since_seq():
    j = fresh()
    j.emit("drive.suspect", drive="/d/0", set=0)
    j.emit("net.partition", rule="both", peers="a|b")
    j.emit("registry.fork", epoch=1, forks=1)
    assert [e["class"] for e in j.recent()] == [
        "drive.suspect", "net.partition", "registry.fork"]
    assert [e["class"] for e in j.recent(classes={"net.partition"})] \
        == ["net.partition"]
    assert [e["class"] for e in j.recent(subsystems={"drive"})] == \
        ["drive.suspect"]
    crit = eventlog.sev_rank("crit")
    assert [e["class"] for e in j.recent(min_sev=crit)] == \
        ["registry.fork"]
    assert [e["class"] for e in j.recent(since_seq=2)] == \
        ["registry.fork"]
    assert len(j.recent(1)) == 1


def test_emit_respects_kill_switch(monkeypatch):
    j = fresh()
    monkeypatch.setenv("MINIO_TPU_EVENTLOG", "off")
    assert j.emit("drive.suspect", drive="/d/0", set=0) is None
    assert j.dropped_total == 1 and j.recent() == []
    monkeypatch.setenv("MINIO_TPU_EVENTLOG", "on")
    assert j.emit("drive.suspect", drive="/d/0", set=0) is not None


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------

def test_persistence_roundtrip(tmp_path):
    d = str(tmp_path / "eventlog")
    j = fresh()
    j.attach(d, node="n1", segment_events=4, flush_s=30.0)
    for i in range(6):
        j.emit("mrf.enqueue", queued=i)
    j.close()
    segs = [n for n in os.listdir(d) if n.startswith("seg-")]
    assert segs, "close() must persist the pending tail"

    j2 = fresh()
    j2.attach(d, node="n1", segment_events=4, flush_s=30.0)
    replayed = j2.recent()
    assert [e["attrs"]["queued"] for e in replayed] == list(range(6))
    assert j2.seq == 6, "seq must advance past persisted entries"
    # new emits continue the sequence — no seq reuse after restart
    e = j2.emit("mrf.drain", healed=1, failed=0)
    assert e["seq"] == 7
    j2.close()


def test_torn_segment_serves_surviving_prefix(tmp_path):
    d = str(tmp_path / "eventlog")
    j = fresh()
    # big segment_events + long flush_s: segment boundaries are set by
    # the explicit flush() calls, not the background flusher
    j.attach(d, node="n1", segment_events=100, flush_s=30.0)
    j.emit("mrf.enqueue", queued=0)
    j.emit("mrf.enqueue", queued=1)
    j.flush()
    j.emit("mrf.enqueue", queued=2)
    j.emit("mrf.enqueue", queued=3)
    j.close()
    segs = sorted(n for n in os.listdir(d) if n.startswith("seg-"))
    assert len(segs) >= 2
    # tear the LAST segment mid-write (the crash window)
    with open(os.path.join(d, segs[-1]), "wb") as f:
        f.write(b'{"v": 1, "events": [{"cl')
    j2 = fresh()
    j2.attach(d, node="n1")
    got = [e["attrs"]["queued"] for e in j2.recent()]
    assert got == [0, 1], \
        f"torn tail must not hide the surviving prefix: {got}"
    j2.close()


def test_segment_retention_prunes_oldest(tmp_path):
    d = str(tmp_path / "eventlog")
    j = fresh()
    j.attach(d, node="n1", segment_events=100, flush_s=30.0,
             keep_segments=3)
    for i in range(8):
        j.emit("mrf.enqueue", queued=i)
        j.flush()
    j.close()
    segs = [n for n in os.listdir(d) if n.startswith("seg-")]
    assert len(segs) <= 3


# ---------------------------------------------------------------------------
# streaming
# ---------------------------------------------------------------------------

def _lines(chunks) -> list:
    out = []
    for c in chunks:
        if c.strip():
            out.append(json.loads(c))
    return out


def test_stream_backlog_then_idle_end():
    j = fresh()
    for i in range(3):
        j.emit("mrf.enqueue", queued=i)
    got = _lines(j.stream(idle_timeout=0.2, backlog=10))
    assert [e["attrs"]["queued"] for e in got] == [0, 1, 2]


def test_stream_max_entries_cuts_live_feed():
    j = fresh()
    done: list = []

    def consume():
        done.extend(_lines(j.stream(max_entries=2, idle_timeout=5.0,
                                    follow=True)))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while j.hub.subscriber_count == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    j.emit("mrf.enqueue", queued=1)
    j.emit("mrf.enqueue", queued=2)
    t.join(timeout=10)
    assert not t.is_alive() and len(done) == 2


def test_stream_dedupes_peer_echo_by_node_seq():
    """In-process multi-node clusters share ONE journal: a peer graft
    echoes local entries back, and the stream must drop the echo by
    (node, seq) identity."""
    j = fresh()
    j.node = "n1"
    local = j.emit("net.heal", peers="a|b")
    echo = dict(local)

    def peer_iter():
        yield echo
        yield {"ts": echo["ts"], "class": "net.partition",
               "sev": "error", "sub": "net", "node": "n2",
               "attrs": {"rule": "both", "peers": "a|b"}, "seq": 1}

    got = _lines(j.stream(idle_timeout=0.5, backlog=10,
                          peer_subs=lambda: [peer_iter()]))
    keys = [(e["node"], e["class"]) for e in got]
    assert keys.count(("n1", "net.heal")) == 1, keys
    assert ("n2", "net.partition") in keys, keys


def test_stream_filters_apply_to_peer_entries():
    j = fresh()
    j.node = "n1"

    def peer_iter():
        yield {"ts": 1.0, "class": "drive.suspect", "sev": "warn",
               "sub": "drive", "node": "n2",
               "attrs": {"drive": "/d/1", "set": 0}, "seq": 1}
        yield {"ts": 1.1, "class": "net.heal", "sev": "info",
               "sub": "net", "node": "n2", "attrs": {"peers": "a|b"},
               "seq": 2}

    got = _lines(j.stream(idle_timeout=0.5, subsystems={"drive"},
                          peer_subs=lambda: [peer_iter()]))
    assert [e["class"] for e in got] == ["drive.suspect"]


# ---------------------------------------------------------------------------
# bench_trend (satellite)
# ---------------------------------------------------------------------------

def _trend(*argv) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_trend.py"),
         *argv], capture_output=True, text=True, timeout=60)


def test_bench_trend_smoke():
    r = _trend("--smoke")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


def test_bench_trend_gates_on_regression(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(
        {"n": 1, "parsed": {"value": 10.0, "put_p99_ms": 5.0}}))
    new.write_text(json.dumps(
        {"n": 2, "parsed": {"value": 5.0, "put_p99_ms": 5.0}}))
    r = _trend(str(old), str(new), "--threshold", "5")
    assert r.returncode == 1 and "REGRESSED" in r.stdout
    # within threshold -> passes
    r2 = _trend(str(old), str(new), "--threshold", "60")
    assert r2.returncode == 0, r2.stdout
