"""Object-lock (WORM) enforcement + POST-policy browser uploads over the
live server (reference cmd/bucket-object-lock.go, cmd/postpolicyform.go
test intents)."""

from __future__ import annotations

import base64
import datetime as _dt
import hashlib
import hmac
import http.client
import json
import time
import urllib.parse

import pytest

from minio_tpu.features import objectlock as olock
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("locktestkey1", "locktestsecret1")
REGION = "us-east-1"


class Client:
    def __init__(self, port, creds=CREDS):
        self.port, self.creds = port, creds

    def request(self, method, path, query=None, body=b"", headers=None,
                sign=True):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"127.0.0.1:{self.port}"
        if sign:
            payload_hash = hashlib.sha256(body).hexdigest()
            hdrs = sig.sign_v4(method, urllib.parse.quote(path), query,
                               hdrs, payload_hash, self.creds, REGION)
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=30)
        conn.request(method, urllib.parse.quote(path) +
                     (f"?{qs}" if qs else ""), body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        out = {k.lower(): v for k, v in resp.getheaders()}
        conn.close()
        return resp.status, out, data


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("lockdrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    yield srv
    srv.stop()
    sets.close()


@pytest.fixture(scope="module")
def client(server):
    c = Client(server.port)
    st, _, _ = c.request(
        "PUT", "/lockb",
        headers={"x-amz-bucket-object-lock-enabled": "true"})
    assert st == 200
    # lock requires versioning
    c.request("PUT", "/lockb", query={"versioning": ""},
              body=b"<VersioningConfiguration><Status>Enabled"
                   b"</Status></VersioningConfiguration>")
    return c


def _iso(dt_s):
    return olock.iso(time.time() + dt_s)


def test_compliance_retention_blocks_version_delete(client):
    st, h, _ = client.request(
        "PUT", "/lockb/worm1", body=b"keep me",
        headers={olock.MD_MODE: "COMPLIANCE",
                 olock.MD_RETAIN: _iso(3600)})
    assert st == 200
    vid = h.get("x-amz-version-id", "")
    assert vid

    # versioned delete (marker) is fine
    st, _, _ = client.request("DELETE", "/lockb/worm1")
    assert st == 204
    # deleting the LOCKED VERSION is not
    st, _, body = client.request("DELETE", "/lockb/worm1",
                                 query={"versionId": vid})
    assert st == 400 and b"ObjectLocked" in body
    # bypass header cannot unlock COMPLIANCE
    st, _, _ = client.request(
        "DELETE", "/lockb/worm1", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 400


def test_governance_retention_bypass(client):
    st, h, _ = client.request(
        "PUT", "/lockb/gov1", body=b"gov",
        headers={olock.MD_MODE: "GOVERNANCE",
                 olock.MD_RETAIN: _iso(3600)})
    assert st == 200
    vid = h["x-amz-version-id"]
    st, _, _ = client.request("DELETE", "/lockb/gov1",
                              query={"versionId": vid})
    assert st == 400
    # root with the bypass header may delete
    st, _, _ = client.request(
        "DELETE", "/lockb/gov1", query={"versionId": vid},
        headers={"x-amz-bypass-governance-retention": "true"})
    assert st == 204


def test_legal_hold_subresource(client):
    st, h, _ = client.request("PUT", "/lockb/held1", body=b"held")
    vid = h["x-amz-version-id"]
    st, _, _ = client.request(
        "PUT", "/lockb/held1", query={"legal-hold": ""},
        body=b"<LegalHold><Status>ON</Status></LegalHold>")
    assert st == 200
    st, _, body = client.request("GET", "/lockb/held1",
                                 query={"legal-hold": ""})
    assert st == 200 and b"<Status>ON</Status>" in body
    st, _, _ = client.request("DELETE", "/lockb/held1",
                              query={"versionId": vid})
    assert st == 400
    # release the hold, then delete succeeds
    client.request("PUT", "/lockb/held1", query={"legal-hold": ""},
                   body=b"<LegalHold><Status>OFF</Status></LegalHold>")
    st, _, _ = client.request("DELETE", "/lockb/held1",
                              query={"versionId": vid})
    assert st == 204


def test_retention_subresource_and_default(client):
    # bucket default retention applies to new objects
    cfg = (b"<ObjectLockConfiguration><ObjectLockEnabled>Enabled"
           b"</ObjectLockEnabled><Rule><DefaultRetention>"
           b"<Mode>GOVERNANCE</Mode><Days>1</Days>"
           b"</DefaultRetention></Rule></ObjectLockConfiguration>")
    st, _, _ = client.request("PUT", "/lockb", query={"object-lock": ""},
                              body=cfg)
    assert st == 200
    st, h, _ = client.request("PUT", "/lockb/defret", body=b"d")
    vid = h["x-amz-version-id"]
    st, _, body = client.request("GET", "/lockb/defret",
                                 query={"retention": ""})
    assert st == 200 and b"GOVERNANCE" in body
    # shortening active GOVERNANCE retention w/o the bypass header: denied
    st, _, _ = client.request(
        "PUT", "/lockb/defret", query={"retention": ""},
        body=(f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>"
              f"{_iso(60)}</RetainUntilDate></Retention>").encode())
    assert st == 400
    # tightening GOVERNANCE -> COMPLIANCE with a LONGER date: allowed
    st, _, _ = client.request(
        "PUT", "/lockb/defret", query={"retention": ""},
        body=(f"<Retention><Mode>COMPLIANCE</Mode><RetainUntilDate>"
              f"{_iso(2 * 86400)}</RetainUntilDate></Retention>").encode())
    assert st == 200
    # COMPLIANCE retention cannot be shortened...
    st, _, _ = client.request(
        "PUT", "/lockb/defret", query={"retention": ""},
        body=(f"<Retention><Mode>COMPLIANCE</Mode><RetainUntilDate>"
              f"{_iso(60)}</RetainUntilDate></Retention>").encode())
    assert st == 400
    # ...nor its mode changed, even with the governance-bypass header
    st, _, _ = client.request(
        "PUT", "/lockb/defret", query={"retention": ""},
        headers={"x-amz-bypass-governance-retention": "true"},
        body=(f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>"
              f"{_iso(3 * 86400)}</RetainUntilDate></Retention>").encode())
    assert st == 400


def test_check_deletable_fails_closed_on_corrupt_date():
    """An unparsable retain-until date on a locked object must keep it
    locked, not make it deletable (ADVICE r2)."""
    from minio_tpu.features import objectlock as olock
    md = {olock.MD_MODE: "COMPLIANCE", olock.MD_RETAIN: "garbage-date"}
    assert olock.check_deletable(md, bypass_governance=False) is not None
    md = {olock.MD_MODE: "GOVERNANCE", olock.MD_RETAIN: "also-bad"}
    assert olock.check_deletable(md, bypass_governance=False) is not None
    # governance bypass still applies
    assert olock.check_deletable(md, bypass_governance=True) is None


def test_governance_retention_bypass_header(client):
    st, h, _ = client.request(
        "PUT", "/lockb/govbp", body=b"g",
        headers={"x-amz-object-lock-mode": "GOVERNANCE",
                 "x-amz-object-lock-retain-until-date": _iso(86400)})
    assert st == 200
    # with bypass header (root holds BypassGovernanceRetention): shorten OK
    st, _, _ = client.request(
        "PUT", "/lockb/govbp", query={"retention": ""},
        headers={"x-amz-bypass-governance-retention": "true"},
        body=(f"<Retention><Mode>GOVERNANCE</Mode><RetainUntilDate>"
              f"{_iso(60)}</RetainUntilDate></Retention>").encode())
    assert st == 200


# ---------------------------------------------------------------------------
# POST policy upload
# ---------------------------------------------------------------------------

def _post_form(client, bucket, fields, file_bytes,
               filename="upload.bin"):
    boundary = "testboundary12345"
    parts = []
    for k, v in fields.items():
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f'name="{k}"\r\n\r\n{v}\r\n'.encode())
    parts.append(
        f"--{boundary}\r\nContent-Disposition: form-data; name=\"file\"; "
        f'filename="{filename}"\r\n'
        f"Content-Type: application/octet-stream\r\n\r\n".encode()
        + file_bytes + b"\r\n")
    parts.append(f"--{boundary}--\r\n".encode())
    body = b"".join(parts)
    conn = http.client.HTTPConnection("127.0.0.1", client.port,
                                      timeout=30)
    conn.request("POST", f"/{bucket}", body=body, headers={
        "Host": f"127.0.0.1:{client.port}",
        "Content-Type": f"multipart/form-data; boundary={boundary}",
        "Content-Length": str(len(body))})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _signed_policy_fields(key_prefix, max_size=1 << 20):
    t = _dt.datetime.now(_dt.timezone.utc)
    datestamp = t.strftime("%Y%m%d")
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    credential = f"{CREDS.access_key}/{datestamp}/{REGION}/s3/aws4_request"
    policy = {
        "expiration": (t + _dt.timedelta(hours=1)).strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
        "conditions": [
            {"bucket": "postb"},
            ["starts-with", "$key", key_prefix],
            ["content-length-range", 1, max_size],
            {"x-amz-credential": credential},
            {"x-amz-date": amz_date},
        ],
    }
    policy_b64 = base64.b64encode(
        json.dumps(policy).encode()).decode()
    skey = sig.signing_key(CREDS.secret_key, datestamp, REGION, "s3")
    signature = hmac.new(skey, policy_b64.encode(),
                         hashlib.sha256).hexdigest()
    return {"key": key_prefix + "${filename}", "policy": policy_b64,
            "x-amz-credential": credential, "x-amz-date": amz_date,
            "x-amz-signature": signature, "bucket": "postb"}


def test_post_policy_upload(client, server):
    assert client.request("PUT", "/postb")[0] == 200
    fields = _signed_policy_fields("uploads/")
    st, _ = _post_form(client, "postb", fields, b"posted bytes",
                       filename="hello.txt")
    assert st == 204
    st, _, got = client.request("GET", "/postb/uploads/hello.txt")
    assert st == 200 and got == b"posted bytes"


def test_post_policy_rejects_bad_signature(client):
    fields = _signed_policy_fields("uploads/")
    fields["x-amz-signature"] = "0" * 64
    st, _ = _post_form(client, "postb", fields, b"nope")
    assert st == 403


def test_post_policy_enforces_conditions(client):
    # key outside the allowed prefix
    fields = _signed_policy_fields("uploads/")
    fields["key"] = "outside/file.txt"
    st, _ = _post_form(client, "postb", fields, b"x")
    assert st == 403
    # file too large
    fields = _signed_policy_fields("uploads/", max_size=4)
    st, _ = _post_form(client, "postb", fields, b"toolarge")
    assert st == 400


def test_post_policy_bound_to_request_bucket(client):
    """A policy signed with {"bucket": "postb"} must not be replayable
    against another bucket, even when the client supplies a matching
    'bucket' form field (ADVICE r2: server injects the URL bucket)."""
    assert client.request("PUT", "/otherb")[0] == 200
    fields = _signed_policy_fields("uploads/")
    # form field says postb (matches the policy) but the URL says otherb
    st, _ = _post_form(client, "otherb", fields, b"replayed")
    assert st == 403
    st, _, _ = client.request("GET", "/otherb/uploads/upload.bin")
    assert st == 404


def test_post_policy_requires_expiration(client):
    fields = _signed_policy_fields("uploads/")
    doc = json.loads(base64.b64decode(fields["policy"]))
    del doc["expiration"]
    policy_b64 = base64.b64encode(json.dumps(doc).encode()).decode()
    t = _dt.datetime.now(_dt.timezone.utc)
    datestamp = t.strftime("%Y%m%d")
    skey = sig.signing_key(CREDS.secret_key, datestamp, REGION, "s3")
    fields["policy"] = policy_b64
    fields["x-amz-signature"] = hmac.new(
        skey, policy_b64.encode(), hashlib.sha256).hexdigest()
    st, _ = _post_form(client, "postb", fields, b"forever")
    assert st == 400