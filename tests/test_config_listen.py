"""Config KV system (env overrides, encrypted persistence, history/
rollback, live apply) + ListenBucketNotification streaming."""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
import urllib.parse

import pytest

from minio_tpu.config import ConfigSys
from minio_tpu.config.kv import ConfigError, _decrypt, _encrypt
from minio_tpu.object.fs import FSObjects
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("cfgtestkey12", "cfgtestsecret12")


def test_config_defaults_and_set(tmp_path):
    fs = FSObjects(str(tmp_path / "c"))
    cfg = ConfigSys(fs, secret="topsecret")
    assert cfg.get("region", "name") == "us-east-1"
    assert cfg.get("compression", "enable") == "off"
    cfg.set_kv("compression", enable="on")
    assert cfg.get("compression", "enable") == "on"

    # fresh instance over the same layer sees the persisted value
    cfg2 = ConfigSys(fs, secret="topsecret")
    assert cfg2.get("compression", "enable") == "on"

    # wrong secret: undecryptable, not silently defaulted
    with pytest.raises(ConfigError):
        ConfigSys(fs, secret="WRONG")


def test_config_unknown_keys_rejected(tmp_path):
    cfg = ConfigSys()
    with pytest.raises(ConfigError):
        cfg.set_kv("compression", bogus="1")
    with pytest.raises(ConfigError):
        cfg.set_kv("nosuchsubsys", enable="on")
    with pytest.raises(ConfigError):
        cfg.get("api", "bogus")


def test_config_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv("MINIO_REGION_NAME", "eu-central-7")
    cfg = ConfigSys()
    assert cfg.get("region", "name") == "eu-central-7"


def test_config_history_rollback(tmp_path):
    fs = FSObjects(str(tmp_path / "h"))
    cfg = ConfigSys(fs, secret="s3cr3t4hist")
    cfg.set_kv("region", name="r1")     # nothing stored yet: no snapshot
    cfg.set_kv("region", name="r2")     # snapshots the r1 blob
    cfg.set_kv("region", name="r3")     # snapshots the r2 blob
    entries = cfg.history()
    assert len(entries) == 2
    cfg.restore(entries[0])             # oldest snapshot = r1
    assert cfg.get("region", "name") == "r1"


def test_config_encryption_roundtrip():
    blob = _encrypt("k", b"hello")
    assert _decrypt("k", blob) == b"hello"
    with pytest.raises(Exception):
        _decrypt("other", blob)


def test_config_apply_live(tmp_path):
    from minio_tpu.s3.handlers import S3ApiHandlers
    fs = FSObjects(str(tmp_path / "a"))
    api = S3ApiHandlers(fs, creds=CREDS)
    cfg = ConfigSys(fs, secret=CREDS.secret_key)
    cfg.set_kv("region", name="ap-moon-1")
    cfg.set_kv("compression", enable="on")
    cfg.set_kv("audit_webhook", enable="on",
               endpoint="http://127.0.0.1:1/audit")
    cfg.apply(api, trace=api.trace)
    assert api.region == "ap-moon-1"
    assert api.compression_enabled
    assert api.trace.audit_webhook == "http://127.0.0.1:1/audit"


def test_admin_config_endpoints(tmp_path):
    from minio_tpu.s3.admin import mount_admin
    fs = FSObjects(str(tmp_path / "adm"))
    srv = S3Server(fs, creds=CREDS).start()
    mount_admin(srv)
    try:
        def req(method, path, query=None, body=b""):
            query = {k: [v] for k, v in (query or {}).items()}
            qs = urllib.parse.urlencode(
                {k: v[0] for k, v in query.items()})
            hdrs = {"host": f"127.0.0.1:{srv.port}"}
            hdrs = sig.sign_v4(method, path, query, hdrs,
                               hashlib.sha256(body).hexdigest(), CREDS,
                               "us-east-1")
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request(method, path + (f"?{qs}" if qs else ""),
                         body=body, headers=hdrs)
            r = conn.getresponse()
            data = r.read()
            conn.close()
            return r.status, data

        st, body = req("GET", "/minio/admin/v3/get-config")
        assert st == 200
        assert json.loads(body)["compression"]["enable"] == "off"
        st, _ = req("PUT", "/minio/admin/v3/set-config",
                    query={"subsys": "compression"},
                    body=json.dumps({"enable": "on"}).encode())
        assert st == 200
        st, body = req("GET", "/minio/admin/v3/get-config")
        assert json.loads(body)["compression"]["enable"] == "on"
        # a second write snapshots the first blob into history (NOT the
        # region: set-config applies live, and changing the region would
        # invalidate this client's SigV4 scope — which is correct)
        st, _ = req("PUT", "/minio/admin/v3/set-config",
                    query={"subsys": "scanner"},
                    body=json.dumps({"interval": "120s"}).encode())
        assert st == 200
        # bad values are rejected before persisting
        st, _ = req("PUT", "/minio/admin/v3/set-config",
                    query={"subsys": "api"},
                    body=json.dumps({"requests_max": "abc"}).encode())
        assert st == 400
        st, body = req("GET", "/minio/admin/v3/config-history")
        assert st == 200 and json.loads(body)["entries"]
    finally:
        srv.stop()


def test_listen_bucket_notification(tmp_path):
    from minio_tpu.features import EventNotifier
    fs = FSObjects(str(tmp_path / "ln"))
    srv = S3Server(fs, creds=CREDS).start()
    srv.api.events = EventNotifier(srv.api.bucket_meta)
    try:
        fs.make_bucket("lb")
        got = []
        done = threading.Event()

        def listen():
            path = "/lb"
            query = {"events": ["s3:ObjectCreated:*"], "prefix": ["logs/"],
                     "idle": ["3"]}
            qs = urllib.parse.urlencode(
                {k: v[0] for k, v in query.items()})
            hdrs = {"host": f"127.0.0.1:{srv.port}"}
            hdrs = sig.sign_v4("GET", path, query, hdrs,
                               hashlib.sha256(b"").hexdigest(), CREDS,
                               "us-east-1")
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=30)
            conn.request("GET", f"{path}?{qs}", headers=hdrs)
            resp = conn.getresponse()
            buf = b""
            while True:
                chunk = resp.read(1)
                if not chunk:
                    break
                buf += chunk
                if b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    got.append(json.loads(line))
                    break
            conn.close()
            done.set()

        t = threading.Thread(target=listen, daemon=True)
        t.start()
        time.sleep(0.3)     # let the listener subscribe
        # filtered out: wrong prefix; then a match
        srv.api.events.send("s3:ObjectCreated:Put", "lb", "other/x")
        srv.api.events.send("s3:ObjectCreated:Put", "lb", "logs/hit")
        assert done.wait(10)
        assert got and got[0]["Records"][0]["s3"]["object"]["key"] == \
            "logs/hit"
    finally:
        srv.api.events.close()
        srv.stop()