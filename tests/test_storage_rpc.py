"""Storage RPC tests: remote StorageAPI verbs client<->server in one
process (the reference's cmd/storage-rest_test.go pattern), then a full
erasure object engine over remote drives."""

from __future__ import annotations

import hashlib
import io

import pytest

from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                               StorageRPCServer,
                                               fi_from_dict, fi_to_dict)
from minio_tpu.distributed.transport import RPCServer
from minio_tpu.storage import errors as serr
from minio_tpu.storage.datatypes import (ChecksumInfo, FileInfo,
                                         new_file_info)
from minio_tpu.storage import new_format_erasure_v3
from minio_tpu.storage.xl_storage import XLStorage

AK, SK = "nodekey", "nodesecret12345"
N = 6


@pytest.fixture()
def cluster(tmp_path):
    """One serving node with N formatted local drives + N remote
    clients."""
    fmts = new_format_erasure_v3(1, N)
    locals_ = {}
    for i in range(N):
        d = XLStorage(str(tmp_path / f"d{i}"))
        d.write_format(fmts[0][i])
        locals_[f"/d{i}"] = d
    srv = StorageRPCServer(locals_, AK, SK)
    host = RPCServer().start()
    host.mount(srv.handler)
    remotes = [RemoteStorage("127.0.0.1", host.port, f"/d{i}", AK, SK)
               for i in range(N)]
    yield locals_, remotes
    for r in remotes:
        r.close()
    host.stop()
    for d in locals_.values():
        d.close()


def test_vol_verbs(cluster):
    _, remotes = cluster
    r = remotes[0]
    r.make_vol("vol1")
    assert "vol1" in [v.name for v in r.list_vols()]
    assert r.stat_vol("vol1").name == "vol1"
    with pytest.raises(serr.VolumeExists):
        r.make_vol("vol1")
    r.delete_vol("vol1")
    with pytest.raises(serr.VolumeNotFound):
        r.stat_vol("vol1")


def test_file_verbs(cluster):
    _, remotes = cluster
    r = remotes[1]
    r.make_vol("v")
    r.write_all("v", "f.bin", b"hello remote")
    assert r.read_all("v", "f.bin") == b"hello remote"
    assert r.read_file("v", "f.bin", 6, 6) == b"remote"
    r.append_file("v", "f.bin", b"!more")
    assert r.read_all("v", "f.bin") == b"hello remote!more"
    r.create_file("v", "dir/stream.bin", 4, io.BytesIO(b"abcd"))
    assert r.read_all("v", "dir/stream.bin") == b"abcd"
    r.rename_file("v", "dir/stream.bin", "v", "dir/renamed.bin")
    assert r.read_all("v", "dir/renamed.bin") == b"abcd"
    assert "dir/" in r.list_dir("v", "")
    r.delete_file("v", "f.bin")
    with pytest.raises(serr.FileNotFound):
        r.read_all("v", "f.bin")


def test_metadata_verbs(cluster):
    _, remotes = cluster
    r = remotes[2]
    r.make_vol("v")
    fi = new_file_info("v/obj", 4, 2)
    fi.volume, fi.name = "v", "obj"
    fi.size = 42
    fi.mod_time = 1234567890.5
    fi.data_dir = "11111111-2222-3333-4444-555555555555"
    fi.metadata = {"etag": "deadbeef", "content-type": "x/y"}
    fi.add_object_part(1, "deadbeef", 42, 42)
    fi.erasure.checksums = [ChecksumInfo(1, "highwayhash256S", b"")]
    r.write_metadata("v", "obj", fi)
    got = r.read_version("v", "obj")
    assert got.size == 42
    assert got.metadata["etag"] == "deadbeef"
    assert got.erasure.data_blocks == 4
    assert [v.name for v in r.read_versions("v", "obj")] == ["obj"]
    # walk sees it
    names = [w.name for w in r.walk("v")]
    assert "obj" in names
    r.delete_version("v", "obj", got)
    with pytest.raises((serr.FileNotFound, serr.FileVersionNotFound)):
        r.read_version("v", "obj")


def test_fi_codec_roundtrip():
    fi = new_file_info("b/o", 12, 4)
    fi.volume, fi.name, fi.size = "b", "o", 999
    fi.metadata = {"etag": "abc", "x": "y"}
    fi.add_object_part(1, "abc", 999, 999)
    fi.erasure.checksums = [ChecksumInfo(1, "sha256", b"\x01\x02")]
    back = fi_from_dict(fi_to_dict(fi))
    assert back.erasure.distribution == fi.erasure.distribution
    assert back.erasure.checksums[0].hash == b"\x01\x02"
    assert back.parts[0].size == 999
    assert back.metadata == fi.metadata


def test_network_error_is_disk_not_found(cluster):
    _, remotes = cluster
    dead = RemoteStorage("127.0.0.1", 1, "/d0", AK, SK, timeout=0.5)
    with pytest.raises(serr.DiskNotFound):
        dead.read_all("v", "x")
    assert not dead.is_online()


def test_auth_failure(cluster):
    _, remotes = cluster
    bad = RemoteStorage("127.0.0.1", remotes[0].rc.port, "/d0", AK,
                        "wrongsecret1234")
    with pytest.raises(serr.UnexpectedError):
        bad.list_vols()


def test_erasure_engine_over_remote_drives(cluster):
    """The full PUT/GET/heal path where every drive is an RPC client —
    the reference's distributed XL over storage REST."""
    from minio_tpu.object import ErasureSetObjects

    locals_, remotes = cluster
    eng = ErasureSetObjects(list(remotes), data_shards=4, parity_shards=2,
                            block_size=1 << 16)
    eng.make_bucket("rb")
    data = b"remote drive payload " * 9973
    info = eng.put_object("rb", "obj", data)
    assert info.etag == hashlib.md5(data).hexdigest()
    _, it = eng.get_object("rb", "obj")
    assert b"".join(it) == data

    # kill one remote drive's data dir and heal through RPC
    import shutil
    victim = locals_["/d0"]
    shutil.rmtree(victim.root + "/rb", ignore_errors=True)
    _, it = eng.get_object("rb", "obj")
    assert b"".join(it) == data        # reconstructs around the hole
    eng.heal_object("rb", "obj")
    _, it = eng.get_object("rb", "obj")
    assert b"".join(it) == data

    objs, _, _ = eng.list_objects("rb")
    assert [o.name for o in objs] == ["obj"]
    eng.delete_object("rb", "obj")
    eng.delete_bucket("rb")
