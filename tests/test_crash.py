"""Crash-consistency matrix: real-subprocess SIGKILL/restart tests.

For every registered crashpoint (utils/crashpoint.py) the harness
(tests/harness/proc.py) boots a REAL ``python -m minio_tpu server``
process, seeds acknowledged state, restarts armed
(``MINIO_TPU_CRASHPOINT=<name>[:n]`` → hard ``os._exit`` at the named
instruction), triggers the covering operation, waits for the process
to die, reboots clean and asserts the durability contract:

  * every acknowledged write is readable byte-identical;
  * the crashed operation's object is ABSENT or COMPLETE — never torn;
  * ``fsck --repair`` converges the tree to zero unrepaired findings
    and a second audit is fully clean.

The whole matrix is ``slow`` (tier-1 excludes it); ``test_crash_smoke``
is the 3-point CI subset the tooling satellite pins. The fast tests at
the bottom assert the matrix COVERS the registry (a new crashpoint
without a crash test is a test failure, not a silent gap) and pin the
crashpoint module's own semantics.
"""

from __future__ import annotations

import os
import time

import pytest

from minio_tpu.utils import crashpoint
from tests.harness.proc import (ACCESS_KEY, CRASH_EXIT_CODE, SECRET_KEY,
                                ProcNode, expect_request_death)

BUCKET = "bkt"
SEED = b"s" * 4096
CRASH_BODY = b"c" * 8192
PART1 = b"p" * (5 * 1024 * 1024)
PART2 = b"q" * 1024

# ---------------------------------------------------------------------------
# matrix scenarios
# ---------------------------------------------------------------------------

def _put_crash(n: ProcNode) -> None:
    expect_request_death(lambda: n.put(BUCKET, "crash", CRASH_BODY))


def _mpu_crash(n: ProcNode) -> None:
    expect_request_death(
        lambda: n.multipart(BUCKET, "mp", [PART1, PART2]))


def _metacache_kick(n: ProcNode) -> None:
    """One acked PUT while no index exists (nothing can crash yet),
    then a listing serve (builds the index → dirty → persist due) and
    one more PUT (journals the delta the drainer claims). The armed
    persist/drain point fires on the BACKGROUND loop — possibly while
    one of these client calls is still on the wire, so each may die
    with the server."""
    n.put(BUCKET, "acked-pre-build", SEED)
    expect_request_death(lambda: n.list_keys(BUCKET))
    expect_request_death(lambda: n.put(BUCKET, "during", SEED))


def _tier_add(n: ProcNode) -> None:
    path = os.path.join(n.workdir, "tier1")
    expect_request_death(
        lambda: n.admin().add_tier("t1", "fs", path=path))


def _repl_target_add(n: ProcNode) -> None:
    expect_request_death(
        lambda: n.admin().add_replicate_target(
            BUCKET, "127.0.0.1", 1, BUCKET, ACCESS_KEY, SECRET_KEY))


def _qos_set(n: ProcNode) -> None:
    expect_request_death(
        lambda: n.admin().qos_set("alice", share=2.0, rps=10.0))


def _notify_target_add(n: ProcNode) -> None:
    expect_request_death(
        lambda: n.admin().add_notify_target(
            endpoint="http://127.0.0.1:1/hook"))


def _verify_notify_registry(n: ProcNode) -> None:
    # the interrupted epoch either fully landed or fully rolled away —
    # and the registry still takes writes afterwards
    got = n.admin().notify_status()
    assert len(got["targets"]) <= 1, got["targets"]
    arn = n.admin().add_notify_target(name="after",
                                      endpoint="http://127.0.0.1:1/h2")
    after = n.admin().notify_status()
    assert after["epoch"] > got["epoch"]
    assert arn in {t["arn"] for t in after["targets"]}


def _verify_qos_registry(n: ProcNode) -> None:
    # the interrupted epoch either fully landed or fully rolled away —
    # and the registry still takes writes afterwards
    got = n.admin().qos_get()
    names = {b["name"] for b in got["tenants"]}
    assert names <= {"alice"}, names
    epoch = n.admin().qos_set("bob", rps=5.0)["epoch"]
    assert epoch > got["epoch"]


def _seed_many(n: ProcNode) -> None:
    for i in range(6):
        n.put(BUCKET, f"obj{i}", bytes([65 + i]) * 1500)


def _start_drain(n: ProcNode) -> None:
    expect_request_death(lambda: n.admin().start_rebalance(1))


def _verify_many(n: ProcNode) -> None:
    for i in range(6):
        assert n.get(BUCKET, f"obj{i}") == bytes([65 + i]) * 1500, \
            f"acked obj{i} lost"


def _verify_drain_resumes(n: ProcNode) -> None:
    """Boot auto-resumes a drain left pending (the pool is still
    marked draining in the persisted epoch doc)."""
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        st = n.admin().rebalance_status().get("rebalance", {})
        if st.get("status") in ("complete", "completed"):
            break
        assert st.get("status") != "failed", st
        time.sleep(0.5)
    else:
        raise AssertionError(f"drain never completed: {st}")
    _verify_many(n)


def _verify_metacache(n: ProcNode) -> None:
    assert n.get(BUCKET, "acked-pre-build") == SEED, \
        "write acked before the crash is unreadable after restart"
    keys = n.list_keys(BUCKET)
    assert {"seed", "acked-pre-build"} <= set(keys), \
        f"acked writes missing from the post-restart listing: {keys}"
    # the in-flight PUT is absent or complete, and the listing agrees
    # with readability either way (no half-indexed ghost)
    assert ("during" in keys) == n.exists(BUCKET, "during")


_MC_ENV = {"MINIO_TPU_METACACHE_PERSIST_S": "0",
           "MINIO_TPU_METACACHE_FLUSH_S": "0.05"}

# name → scenario. Keys are crashpoint SPECS (":<nth>" selects the hit
# for per-disk / per-pool fan-out points).
CASES = {
    "put.shards.before_meta": dict(trigger=_put_crash,
                                   atomic=[("crash", CRASH_BODY)]),
    "put.meta.before_rename": dict(trigger=_put_crash,
                                   atomic=[("crash", CRASH_BODY)]),
    "put.rename.partial:2": dict(trigger=_put_crash,
                                 atomic=[("crash", CRASH_BODY)]),
    "storage.rename_data.before_meta": dict(
        trigger=_put_crash, atomic=[("crash", CRASH_BODY)]),
    "multipart.part.before_rename": dict(
        trigger=_mpu_crash, atomic=[("mp", PART1 + PART2)]),
    "multipart.complete.before_rename": dict(
        trigger=_mpu_crash, atomic=[("mp", PART1 + PART2)]),
    "multipart.complete.rename.partial:2": dict(
        trigger=_mpu_crash, atomic=[("mp", PART1 + PART2)]),
    "metacache.persist.segment": dict(
        trigger=_metacache_kick, env=_MC_ENV, wait_exit=90,
        atomic=[("during", SEED)], verify=_verify_metacache),
    "metacache.persist.before_manifest": dict(
        trigger=_metacache_kick, env=_MC_ENV, wait_exit=90,
        atomic=[("during", SEED)], verify=_verify_metacache),
    "metacache.journal.drain": dict(
        trigger=_metacache_kick, env=_MC_ENV, wait_exit=90,
        atomic=[("during", SEED)], verify=_verify_metacache),
    "topology.save.pool": dict(pools=2, boot_crash=True),
    "tier.save.pool": dict(trigger=_tier_add),
    "replicate.registry.save.pool": dict(trigger=_repl_target_add),
    "qos.save.pool": dict(trigger=_qos_set, verify=_verify_qos_registry),
    "notify.registry.save.pool": dict(trigger=_notify_target_add,
                                      verify=_verify_notify_registry),
    "rebalance.checkpoint": dict(
        pools=2, seed=_seed_many, trigger=_start_drain, wait_exit=120,
        env={"MINIO_TPU_REBALANCE_CHECKPOINT_EVERY": "1"},
        verify=_verify_drain_resumes),
}

# registered points exercised OUTSIDE the subprocess matrix: the
# two-site tests below (resync/push need a live peer) and the
# in-process torn-write/MRF tests in tests/test_fsck.py
COVERED_ELSEWHERE = {
    "resync.checkpoint": "test_crash.py::test_two_site_resync_crash",
    "replicate.push.before_apply":
        "test_crash.py::test_two_site_push_crash",
    "mrf.drain.before_heal": "test_fsck.py::test_mrf_drain_crash",
    "storage.write_all.commit":
        "test_fsck.py::test_torn_write_injection",
    "eventlog.persist.segment":
        "test_incidents.py::"
        "test_sigkill_mid_segment_persist_serves_prefix",
    "notify.queue.persist":
        "test_notify_proc.py::test_queue_persist_crashpoint_kill_replay",
}

SMOKE_POINTS = ("put.meta.before_rename",
                "multipart.complete.before_rename",
                "metacache.persist.before_manifest")


def run_case(tmp_path, spec: str) -> None:
    case = CASES[spec]
    env = case.get("env")
    n = ProcNode(str(tmp_path), name="n", pools=case.get("pools", 1))
    try:
        # phase 1 (unarmed): seed acknowledged state
        n.start(extra_env=env)
        n.s3().make_bucket(BUCKET)
        n.put(BUCKET, "seed", SEED)
        case.get("seed", lambda node: None)(n)
        n.stop()

        # phase 2 (armed): trigger, die at the named instruction
        if case.get("boot_crash"):
            # the point fires inside boot itself (epoch persist on
            # pool attach) — no client trigger, just wait for death
            n.start(crashpoint=spec, extra_env=env, wait=False)
        else:
            n.start(crashpoint=spec, extra_env=env)
            case["trigger"](n)
        rc = n.wait_exit(case.get("wait_exit", 60))
        assert rc == CRASH_EXIT_CODE, (rc, n.tail_log())

        # phase 3 (unarmed): restart, assert the durability contract
        n.start(extra_env=env)
        assert n.get(BUCKET, "seed") == SEED, \
            f"{spec}: acknowledged write lost across the crash"
        for key, body in case.get("atomic", ()):
            if n.exists(BUCKET, key):
                got = n.get(BUCKET, key)
                assert got == body, \
                    f"{spec}: {key} served TORN ({len(got)} bytes)"
        case.get("verify", lambda node: None)(n)
        rep = n.fsck(repair=True)
        assert rep["unrepaired"] == 0, (spec, rep)
        rep2 = n.fsck(repair=False)
        assert rep2["clean"], (spec, rep2)
        n.stop()
    finally:
        n.close()


@pytest.mark.slow
@pytest.mark.parametrize("spec", sorted(CASES))
def test_crash_matrix(tmp_path, spec):
    run_case(tmp_path, spec)


@pytest.mark.slow
def test_crash_smoke(tmp_path):
    """The 3-point CI subset (tooling satellite): one PUT commit, one
    multipart complete, one metacache persist — the cheapest spanning
    set of the three commit families."""
    for i, spec in enumerate(SMOKE_POINTS):
        run_case(tmp_path / str(i), spec)


# ---------------------------------------------------------------------------
# two-process active-active site pair (ROADMAP item 4 remainder)
# ---------------------------------------------------------------------------

def _counter_total(node: ProcNode, family: str) -> float:
    total = 0.0
    for line in node.admin().metrics_text().splitlines():
        if line.startswith(family) and " " in line:
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _wait_converged(a: ProcNode, b: ProcNode, timeout: float = 90.0
                    ) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        la, lb = a.listing(BUCKET), b.listing(BUCKET)
        if la and la == lb:
            return
        time.sleep(0.5)
    raise AssertionError(
        f"sites never converged:\nA={a.listing(BUCKET)}\n"
        f"B={b.listing(BUCKET)}")


def _pair(tmp_path) -> tuple[ProcNode, ProcNode]:
    a = ProcNode(str(tmp_path / "a"), name="a")
    b = ProcNode(str(tmp_path / "b"), name="b")
    return a, b


def _add_target(a: ProcNode, b: ProcNode) -> str:
    return a.admin().add_replicate_target(
        BUCKET, "127.0.0.1", b.port, BUCKET, ACCESS_KEY, SECRET_KEY)


def _resync_to_convergence(a: ProcNode, b: ProcNode, arn: str,
                           timeout: float = 120.0) -> None:
    """Start (or restart) the resync and poll until listings match —
    re-kicking a finished-but-incomplete resync, since a crashed
    worker loses its in-memory queue by design (resync is the
    backstop)."""
    deadline = time.monotonic() + timeout
    a.admin().start_replicate_resync(arn)
    while time.monotonic() < deadline:
        la, lb = a.listing(BUCKET), b.listing(BUCKET)
        if la and la == lb:
            return
        st = a.admin().replicate_resync_status() or {}
        status = (st or {}).get("status", "")
        if status in ("completed", "failed", ""):
            a.admin().start_replicate_resync(arn)
        time.sleep(1.0)
    raise AssertionError(
        f"resync never converged:\nA={a.listing(BUCKET)}\n"
        f"B={b.listing(BUCKET)}\nstatus={st}")


@pytest.mark.slow
def test_two_site_pair_kill_target_mid_resync(tmp_path):
    """ROADMAP item 4 remainder: a two-PROCESS site pair over the
    HTTP replication client under load, the TARGET site SIGKILLed
    mid-resync; after restart the pair converges to identical
    listings, replica-write counters stay flat across an extra
    cycle (loop suppression), and both sites end fsck-clean."""
    a, b = _pair(tmp_path)
    try:
        a.start()
        b.start()
        a.s3().make_bucket(BUCKET)
        b.s3().make_bucket(BUCKET)
        bodies = {f"k{i:02d}": bytes([48 + i]) * 1500 for i in range(12)}
        for k, v in bodies.items():
            a.put(BUCKET, k, v)
        arn = _add_target(a, b)
        a.admin().start_replicate_resync(arn)
        time.sleep(0.4)                       # mid-resync
        b.kill()                              # SIGKILL the target
        # load keeps arriving on the surviving site
        for i in range(12, 16):
            bodies[f"k{i:02d}"] = bytes([48 + i]) * 1500
            a.put(BUCKET, f"k{i:02d}", bodies[f"k{i:02d}"])
        b.start()
        _resync_to_convergence(a, b, arn)
        for k, v in bodies.items():
            assert b.get(BUCKET, k) == v, f"replica {k} diverged"
        # loop suppression: an EXTRA full cycle pushes nothing — the
        # replica-write counter across both sites stays flat
        time.sleep(2.0)                       # let in-flight syncs settle
        before = (_counter_total(a, "minio_tpu_repl_replica_writes")
                  + _counter_total(b, "minio_tpu_repl_replica_writes"))
        a.admin().start_replicate_resync(arn)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = a.admin().replicate_resync_status() or {}
            if (st or {}).get("status") in ("completed", ""):
                break
            time.sleep(0.5)
        after = (_counter_total(a, "minio_tpu_repl_replica_writes")
                 + _counter_total(b, "minio_tpu_repl_replica_writes"))
        assert after == before, \
            f"extra cycle re-pushed replicas ({before} -> {after})"
        for node in (a, b):
            rep = node.fsck(repair=True)
            assert rep["unrepaired"] == 0, (node.name, rep)
        a.stop()
        b.stop()
    finally:
        a.close()
        b.close()


def _two_site_source_crash(tmp_path, spec: str, extra_env=None) -> None:
    """Shared driver: the SOURCE site armed with `spec` dies mid-sync,
    restarts, and the pair still converges (checkpoint resume / resync
    backstop), fsck-clean on both sides."""
    a, b = _pair(tmp_path)
    try:
        a.start()
        b.start()
        a.s3().make_bucket(BUCKET)
        b.s3().make_bucket(BUCKET)
        bodies = {f"k{i:02d}": bytes([48 + i]) * 1500 for i in range(8)}
        for k, v in bodies.items():
            a.put(BUCKET, k, v)
        arn = _add_target(a, b)
        a.stop()

        a.start(crashpoint=spec, extra_env=extra_env)
        expect_request_death(
            lambda: a.admin().start_replicate_resync(arn))
        rc = a.wait_exit(90)
        assert rc == CRASH_EXIT_CODE, (rc, a.tail_log())

        a.start(extra_env=extra_env)
        _resync_to_convergence(a, b, arn)
        for k, v in bodies.items():
            assert b.get(BUCKET, k) == v, f"replica {k} diverged"
        for node in (a, b):
            rep = node.fsck(repair=True)
            assert rep["unrepaired"] == 0, (node.name, rep)
        a.stop()
        b.stop()
    finally:
        a.close()
        b.close()


@pytest.mark.slow
def test_two_site_resync_crash(tmp_path):
    _two_site_source_crash(
        tmp_path, "resync.checkpoint",
        extra_env={"MINIO_TPU_REPL_RESYNC_CHECKPOINT_EVERY": "1"})


@pytest.mark.slow
def test_two_site_push_crash(tmp_path):
    _two_site_source_crash(tmp_path, "replicate.push.before_apply")


# ---------------------------------------------------------------------------
# fast (tier-1) tests: registry coverage + crashpoint semantics
# ---------------------------------------------------------------------------

def test_matrix_covers_registry():
    """Every registered crashpoint has a crash test: either a matrix
    entry here or a named owner in COVERED_ELSEWHERE. A new hit site
    without coverage fails THIS fast test, not just the slow tier."""
    matrix = {spec.split(":")[0] for spec in CASES}
    covered = matrix | set(COVERED_ELSEWHERE)
    registered = set(crashpoint.names())
    assert registered - covered == set(), \
        f"crashpoints without a crash test: {registered - covered}"
    assert covered - registered == set(), \
        f"tests name unregistered crashpoints: {covered - registered}"
    assert len(registered) >= 12


def test_smoke_subset_is_valid():
    assert set(SMOKE_POINTS) <= set(CASES)
    assert len(SMOKE_POINTS) == 3


def test_crashpoint_arm_nth_and_disarm():
    crashpoint.disarm()
    crashpoint.arm("put.meta.before_rename", nth=3)
    try:
        crashpoint.hit("put.meta.before_rename")
        crashpoint.hit("put.rename.partial")        # other name: no-op
        crashpoint.hit("put.meta.before_rename")
        assert crashpoint.hits("put.meta.before_rename") == 2
        with pytest.raises(crashpoint.CrashpointAbort):
            crashpoint.hit("put.meta.before_rename")
        # past the Nth hit the point never re-fires (one crash per arm)
        crashpoint.hit("put.meta.before_rename")
    finally:
        crashpoint.disarm()
    crashpoint.hit("put.meta.before_rename")        # disarmed: no-op


def test_crashpoint_env_parse(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_CRASHPOINT",
                       "put.rename.partial:4")
    crashpoint.refresh()
    try:
        assert crashpoint.armed_name() == "put.rename.partial"
        for _ in range(3):
            crashpoint.hit("put.rename.partial")
        assert crashpoint.hits("put.rename.partial") == 3
    finally:
        monkeypatch.delenv("MINIO_TPU_CRASHPOINT")
        crashpoint.refresh()
    assert crashpoint.armed_name() is None


def test_crashpoint_unregistered_env_never_fires(monkeypatch, capsys):
    monkeypatch.setenv("MINIO_TPU_CRASHPOINT", "no.such.point")
    crashpoint.refresh()
    try:
        crashpoint.hit("put.meta.before_rename")    # must not fire
        assert "no.such.point" in capsys.readouterr().err
    finally:
        monkeypatch.delenv("MINIO_TPU_CRASHPOINT")
        crashpoint.refresh()


def test_crashpoint_arm_rejects_unregistered():
    with pytest.raises(KeyError):
        crashpoint.arm("not.a.point")


def test_registry_table_renders():
    table = crashpoint.render_table()
    for name in crashpoint.names():
        assert f"`{name}`" in table
