"""Tracing/audit, pub/sub, dynamic timeouts, disk-ID guard."""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
import urllib.parse

import pytest

from minio_tpu.storage import errors as serr
from minio_tpu.storage.diskid_check import DiskIDCheck
from minio_tpu.storage.format import (FormatErasureV3, read_format_from,
                                      write_format_to)
from minio_tpu.storage.xl_storage import XLStorage
from minio_tpu.utils.dyntimeout import DynamicTimeout
from minio_tpu.utils.pubsub import PubSub


def test_pubsub_fanout_and_drop():
    hub = PubSub(buffer=2)
    s1 = hub.subscribe()
    s2 = hub.subscribe()
    hub.publish("a")
    assert s1.get(0.1) == "a" and s2.get(0.1) == "a"
    s2.close()
    assert hub.subscriber_count == 1
    # overflow drops, publisher never blocks
    for i in range(5):
        hub.publish(i)
    assert s1.get(0.1) == 0 and s1.get(0.1) == 1
    s1.close()


def test_dynamic_timeout_adjusts():
    dt = DynamicTimeout(1.0, 0.1, 8.0)
    for _ in range(16):
        dt.log_failure()
    assert dt.timeout() == pytest.approx(1.25)
    for _ in range(64):
        dt.log_success(0.01)
    assert dt.timeout() < 1.25
    assert dt.timeout() >= 0.1


def test_diskid_check_guards_swapped_drive(tmp_path):
    d = XLStorage(str(tmp_path / "drv"))
    fmt = FormatErasureV3(id="0b671633-6e34-4f31-8ad0-1f8f43d29b88",
                          this="11111111-2222-3333-4444-555555555555",
                          sets=[["11111111-2222-3333-4444-555555555555"]])
    write_format_to(d, fmt)
    guard = DiskIDCheck(d, fmt.this, interval=0.0)  # recheck every call
    guard.make_vol("bkt")
    guard.write_all("bkt", "x", b"1")
    assert guard.read_all("bkt", "x") == b"1"

    # reformat the drive behind the wrapper: calls must fail DiskStale
    import dataclasses
    foreign = dataclasses.replace(
        fmt, this="99999999-2222-3333-4444-555555555555",
        sets=[["99999999-2222-3333-4444-555555555555"]])
    write_format_to(d, foreign)
    with pytest.raises(serr.DiskStale):
        guard.read_all("bkt", "x")


def test_trace_records_requests_and_streams(tmp_path):
    from minio_tpu.object.fs import FSObjects
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server

    creds = Credentials("tracetest123", "tracesecret123")
    fs = FSObjects(str(tmp_path / "tr"))
    srv = S3Server(fs, creds=creds).start()
    try:
        entries = []
        done = threading.Event()

        def consume():
            for line in srv.api.trace.stream(max_entries=2,
                                             idle_timeout=5.0):
                entries.append(json.loads(line))
            done.set()

        threading.Thread(target=consume, daemon=True).start()
        time.sleep(0.1)

        def req(method, path, body=b""):
            hdrs = {"host": f"127.0.0.1:{srv.port}"}
            hdrs = sig.sign_v4(method, path, {}, hdrs,
                               hashlib.sha256(body).hexdigest(), creds,
                               "us-east-1")
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            conn.request(method, path, body=body, headers=hdrs)
            r = conn.getresponse()
            r.read()
            conn.close()
            return r.status

        assert req("PUT", "/trb") == 200
        assert req("PUT", "/trb/o", b"x") == 200
        assert done.wait(10)
        assert len(entries) == 2
        assert entries[0]["method"] == "PUT"
        assert entries[0]["path"] == "/trb"
        assert entries[0]["status"] == 200
        assert entries[0]["duration_ms"] > 0
        assert srv.api.trace.requests_total >= 2
    finally:
        srv.stop()


def test_wiped_drive_still_heals_through_guard(tmp_path):
    """DiskIDCheck must not break the new-disk heal flow."""
    import shutil
    from minio_tpu.object.background import DiskMonitor
    from minio_tpu.object.sets import ErasureSets
    drives = [str(tmp_path / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    sets.make_bucket("b")
    sets.put_object("b", "o", b"guarded" * 1000)
    shutil.rmtree(drives[1])
    mon = DiskMonitor(sets)
    assert mon.scan_once() == 1
    _, stream = sets.get_object("b", "o")
    assert b"".join(stream) == b"guarded" * 1000
    assert mon.scan_once() == 0
    sets.close()