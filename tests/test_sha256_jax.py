"""Bit-identity of the batched device SHA-256 against hashlib across
padding branches (block-boundary lengths, the 56-byte tail case,
multi-block)."""

import hashlib

import numpy as np
import pytest

from minio_tpu.ops.sha256_jax import sha256_batch


@pytest.mark.parametrize("length", [
    0, 1, 3, 55, 56, 57, 63, 64, 65, 119, 120, 127, 128, 200, 1000,
])
def test_sha256_batch_identity(length):
    rng = np.random.default_rng(length)
    n = 4
    data = rng.integers(0, 256, (n, max(length, 1)), dtype=np.uint8)
    data = data[:, :length]
    got = np.asarray(sha256_batch(data))
    assert got.shape == (n, 32)
    for i in range(n):
        assert got[i].tobytes() == hashlib.sha256(
            data[i].tobytes()).digest(), f"row {i} len {length}"


def test_sha256_known_vectors():
    got = np.asarray(sha256_batch(np.frombuffer(b"abc", np.uint8)[None]))
    assert got[0].tobytes().hex() == (
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad")
    got = np.asarray(sha256_batch(np.zeros((1, 0), np.uint8)))
    assert got[0].tobytes().hex() == (
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")


def test_sha256_batch_independence():
    """Different rows produce their own digests (no cross-lane mixing)."""
    a = np.frombuffer(b"hello world, this is row A!!"[:24], np.uint8)
    b = np.frombuffer(b"and this one here is row B!!"[:24], np.uint8)
    got = np.asarray(sha256_batch(np.stack([a, b])))
    assert got[0].tobytes() == hashlib.sha256(a.tobytes()).digest()
    assert got[1].tobytes() == hashlib.sha256(b.tobytes()).digest()