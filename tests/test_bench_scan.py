"""CI smoke for bench.py --ab-select-smoke / --ab-cache-smoke (tiny
device-scan and hot-object-cache A/Bs): each must run end-to-end
inside the tier-1 budget, emit JSON-serializable results, and prove
the plane's load-bearing claims — the select bench asserts device/CPU
byte-identity itself before timing, so what's pinned here is that the
device path actually served (no silent wall-to-wall fallback), that
concurrent requests coalesced through the scheduler's scan verb, and
that cache hits provably skipped the erasure decode path."""

from __future__ import annotations

import json

import bench


def test_select_ab_smoke():
    out = bench.bench_select_ab(streams=(1, 2), rows=3000,
                                queries_per_stream=2)
    json.dumps(out)                     # BENCH-compatible payload
    assert out["config"]["rows"] == 3000
    assert [p["streams"] for p in out["points"]] == [1, 2]
    for p in out["points"]:
        dev = p["device"]
        # every query rode the device plan — the bench raises on any
        # byte divergence, so serves+no-fallbacks == correctness held
        assert dev["device_serves"] == dev["queries"], p
        assert dev["fallbacks"] == 0, p
        assert dev["sched_batches"] >= 1, p
    # 2 concurrent streams x 2 queries through one former: fewer
    # device launches than queries, the coalesced counter rising
    two = out["points"][-1]["device"]
    assert two["sched_batches"] < two["queries"], two
    assert two["sched_coalesced"] >= 1, two
    assert out["max_speedup_x"] > 0


def test_cache_ab_smoke():
    out = bench.bench_cache_ab(objects=8, size=1 << 18, gets=60,
                               streams=2)
    json.dumps(out)                     # BENCH-compatible payload
    assert out["config"]["objects"] == 8
    # cache-off: every GET is an erasure decode stream
    assert out["off"]["decode_streams"] == 60
    # cache-on: hits serve WITHOUT the shard-read/verify/decode path
    # (bytes asserted identical inside the bench); with a 1-hit
    # admission bar over an 80/20 pick the hot set fills once and the
    # decode counter stops moving
    assert out["on"]["cache"]["hits"] > 0
    assert out["on"]["decode_streams"] < 60
    assert out["decode_streams_saved"] == out["on"]["cache"]["hits"]
    assert out["speedup_x"] > 0
