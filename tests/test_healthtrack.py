"""Unit tests for the gray-failure health tracker: windowed
percentiles, adaptive hedge/stall deadlines (clamps + cold-start
ceiling), the quarantine decision (absolute + relative bars), the
suspect → probation → ok state machine, and the metrics surface."""

from __future__ import annotations

import pytest

from minio_tpu.utils import healthtrack as ht
from minio_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _clean_tracker():
    ht.TRACKER.reset()
    yield
    ht.TRACKER.reset()


def feed(key: str, verb: str, values, kind: str = "drive") -> None:
    for v in values:
        ht.TRACKER.observe(kind, key, verb, v)


def test_percentile_windowed():
    feed("d0", "read", [0.001] * 50)
    p = ht.TRACKER.percentile("drive", "d0", 0.95, verbs=("read",))
    assert p == pytest.approx(0.001)
    # the window caps retention: a flood of slow samples displaces old
    feed("d0", "read", [0.5] * 200)
    p = ht.TRACKER.percentile("drive", "d0", 0.5, verbs=("read",))
    assert p == pytest.approx(0.5)


def test_healthy_percentile_excludes_suspects_and_self():
    feed("fast1", "read", [0.001] * 10)
    feed("fast2", "read", [0.002] * 10)
    feed("slow", "read", [0.9] * 10)
    ht.TRACKER.set_state("drive", "slow", ht.STATE_SUSPECT)
    p = ht.TRACKER.healthy_percentile("drive", 0.95, verbs=("read",))
    assert p is not None and p < 0.01
    # exclude= leaves the named entity's samples out too
    p2 = ht.TRACKER.healthy_percentile("drive", 0.95, verbs=("read",),
                                       exclude="fast2")
    assert p2 == pytest.approx(0.001)


def test_hedge_deadline_clamps(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_HEDGE_K", "3")
    monkeypatch.setenv("MINIO_TPU_HEDGE_FLOOR_S", "0.05")
    monkeypatch.setenv("MINIO_TPU_HEDGE_CEIL_S", "2.0")
    # cold start: no samples -> ceiling (never hedge spuriously)
    assert ht.read_hedge_s() == pytest.approx(2.0)
    # healthy p95 * K below the floor -> floor
    feed("d0", "read", [0.001] * 20)
    assert ht.read_hedge_s() == pytest.approx(0.05)
    # in-range -> p95 * K
    ht.TRACKER.reset()
    feed("d0", "read", [0.1] * 20)
    assert ht.read_hedge_s() == pytest.approx(0.3, rel=0.1)
    # off switch
    monkeypatch.setenv("MINIO_TPU_HEDGE", "off")
    assert ht.read_hedge_s() is None


def test_write_stall_deadline(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_WRITE_STALL_CEIL_S", "10")
    assert ht.write_stall_s() == pytest.approx(10.0)   # cold ceiling
    monkeypatch.setenv("MINIO_TPU_QUORUM_ACK", "off")
    assert ht.write_stall_s() is None


def test_should_quarantine_absolute_bar(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_QUAR_LATENCY_S", "0.25")
    monkeypatch.setenv("MINIO_TPU_QUAR_MIN_SAMPLES", "8")
    feed("slow", "read", [0.5] * 6)
    # below the sample bar: no conviction on thin evidence
    assert not ht.TRACKER.should_quarantine("drive", "slow")
    feed("slow", "read", [0.5] * 4)
    assert ht.TRACKER.should_quarantine("drive", "slow")
    feed("fine", "read", [0.01] * 20)
    assert not ht.TRACKER.should_quarantine("drive", "fine")


def test_relative_bar_spares_uniformly_slow_media(monkeypatch):
    """Every drive slow (cheap medium): nobody is an outlier, nobody
    quarantines — the relative ratio raises the threshold."""
    monkeypatch.setenv("MINIO_TPU_QUAR_LATENCY_S", "0.05")
    monkeypatch.setenv("MINIO_TPU_QUAR_MIN_SAMPLES", "8")
    monkeypatch.setenv("MINIO_TPU_QUAR_RATIO", "8")
    for d in ("a", "b", "c"):
        feed(d, "read", [0.1] * 12)
    assert not ht.TRACKER.should_quarantine("drive", "a")
    # now one drive is 10x its peers: convicted
    feed("gray", "read", [1.0] * 12)
    assert ht.TRACKER.should_quarantine("drive", "gray")


def test_probe_state_machine():
    ht.TRACKER.set_state("drive", "d0", ht.STATE_PROBATION)
    assert ht.TRACKER.note_probe("drive", "d0", True) == 1
    assert ht.TRACKER.note_probe("drive", "d0", True) == 2
    # a failed probe re-convicts: back to suspect, count reset
    assert ht.TRACKER.note_probe("drive", "d0", False) == 0
    assert ht.TRACKER.state_of("drive", "d0") == ht.STATE_SUSPECT


def test_snapshot_and_gauge_exposition():
    feed("d0", "read", [0.002] * 5)
    feed("p0", "peer-verb", [0.004] * 3, kind="peer")
    ht.TRACKER.set_state("drive", "d0", ht.STATE_SUSPECT)
    snap = ht.TRACKER.snapshot()
    kinds = {(e["kind"], e["key"]) for e in snap}
    assert ("drive", "d0") in kinds and ("peer", "p0") in kinds
    d0 = next(e for e in snap if e["key"] == "d0")
    assert d0["state"] == ht.STATE_SUSPECT
    assert d0["verbs"]["read"]["n"] == 5
    text = telemetry.REGISTRY.render()
    assert 'minio_tpu_drive_health{disk="d0"} 1' in text
    assert "minio_tpu_drive_latency_seconds_bucket" in text
    assert "minio_tpu_peer_latency_seconds_count" in text
