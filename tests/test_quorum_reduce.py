"""Quorum error-reduction algebra under mixed error populations
(reference reduceReadQuorumErrs/reduceWriteQuorumErrs tests,
cmd/erasure-metadata-utils_test.go): ignored (gone-disk) errors,
offline drives, bitrot, and the exact-quorum boundary on both sides."""

from __future__ import annotations

import pytest

from minio_tpu.object import api_errors, metadata as meta
from minio_tpu.storage import errors as serr

IGN = meta.OBJECT_OP_IGNORED_ERRS


def errs(*groups):
    """errs((None, 4), (serr.FileNotFound, 2)) -> flat error list."""
    out = []
    for cls, n in groups:
        for _ in range(n):
            out.append(None if cls is None else cls("x"))
    return out


# ---------------------------------------------------------------------------
# reduce_errs fundamentals
# ---------------------------------------------------------------------------

def test_reduce_errs_majority_and_tie_prefers_success():
    n, err = meta.reduce_errs(errs((None, 3), (serr.FileNotFound, 2)), ())
    assert n == 3 and err is None
    # exact tie: success wins (a quorum of successes must not be
    # out-voted by an equal count of one error class)
    n, err = meta.reduce_errs(errs((None, 3), (serr.FileNotFound, 3)), ())
    assert n == 3 and err is None
    # error majority: the representative instance comes back
    n, err = meta.reduce_errs(errs((None, 2), (serr.FileNotFound, 4)), ())
    assert n == 4 and isinstance(err, serr.FileNotFound)


def test_reduce_errs_ignored_classes_never_vote():
    population = errs((serr.DiskNotFound, 5), (serr.FileNotFound, 1))
    n, err = meta.reduce_errs(population, IGN)
    assert n == 1 and isinstance(err, serr.FileNotFound)
    # with nothing left after filtering, there is no winner at all
    n, err = meta.reduce_errs(errs((serr.DiskNotFound, 6)), IGN)
    assert n == 0 and err is None


# ---------------------------------------------------------------------------
# write quorum — exact boundary on both sides
# ---------------------------------------------------------------------------

def test_write_quorum_exact_boundary_success_side():
    # exactly quorum successes + counted errors below quorum: success
    population = errs((None, 4), (serr.FileNotFound, 2))
    assert meta.reduce_write_quorum_errs(population, IGN, 4) is None
    # one short of quorum: InsufficientWriteQuorum
    population = errs((None, 3), (serr.FileNotFound, 3))
    err = meta.reduce_write_quorum_errs(population, IGN, 4)
    assert isinstance(err, api_errors.InsufficientWriteQuorum)


def test_write_quorum_exact_boundary_error_side():
    # exactly quorum drives agree on the SAME error: that error wins
    # (the op deterministically failed, not a quorum shortfall)
    population = errs((serr.FileNotFound, 4), (None, 2))
    err = meta.reduce_write_quorum_errs(population, IGN, 4)
    assert isinstance(err, serr.FileNotFound)
    # same error count one short of quorum: shortfall
    population = errs((serr.FileNotFound, 3), (None, 2),
                      (serr.VolumeNotFound, 1))
    err = meta.reduce_write_quorum_errs(population, IGN, 4)
    assert isinstance(err, api_errors.InsufficientWriteQuorum)


def test_write_quorum_offline_drives_do_not_mask_success():
    # parity-many gone drives (ignored) + quorum successes: success,
    # even though successes < quorum + ignored count
    population = errs((None, 4), (serr.DiskNotFound, 2))
    assert meta.reduce_write_quorum_errs(population, IGN, 4) is None
    # gone drives can't *create* quorum either
    population = errs((None, 3), (serr.DiskNotFound, 3))
    err = meta.reduce_write_quorum_errs(population, IGN, 4)
    assert isinstance(err, api_errors.InsufficientWriteQuorum)


def test_write_quorum_mixed_population():
    # ignored + offline + bitrot + success all at once: only counted
    # classes vote; the biggest counted class is the outcome
    population = (errs((None, 2), (serr.DiskNotFound, 1),
                       (serr.FaultyDisk, 1))          # ignored classes
                  + [serr.BitrotHashMismatch("a", "b") for _ in range(3)])
    err = meta.reduce_write_quorum_errs(population, IGN, 3)
    assert isinstance(err, serr.BitrotHashMismatch)


# ---------------------------------------------------------------------------
# read quorum — exact boundary on both sides
# ---------------------------------------------------------------------------

def test_read_quorum_exact_boundary():
    population = errs((None, 4), (serr.FileNotFound, 2))
    assert meta.reduce_read_quorum_errs(population, IGN, 4) is None
    err = meta.reduce_read_quorum_errs(population, IGN, 5)
    assert isinstance(err, api_errors.InsufficientReadQuorum)


def test_read_quorum_bitrot_plus_offline():
    # bitrot on read-quorum-many drives with the rest offline: the
    # bitrot error surfaces (deep heal trigger), not a generic shortfall
    population = (errs((serr.DiskNotFound, 2))
                  + [serr.BitrotHashMismatch("x", "y") for _ in range(4)])
    err = meta.reduce_read_quorum_errs(population, IGN, 4)
    assert isinstance(err, serr.BitrotHashMismatch)


def test_read_quorum_all_drives_gone():
    population = errs((serr.DiskNotFound, 4), (serr.FaultyDisk, 2))
    err = meta.reduce_read_quorum_errs(population, IGN, 1)
    assert isinstance(err, api_errors.InsufficientReadQuorum)


def test_read_quorum_not_found_maps_through():
    # a deleted object: quorum-many FileNotFound must come back as
    # FileNotFound (so callers map to ObjectNotFound), never a quorum
    # failure
    population = errs((serr.FileNotFound, 5), (serr.DiskNotFound, 1))
    err = meta.reduce_read_quorum_errs(population, IGN, 4)
    assert isinstance(err, serr.FileNotFound)


def test_network_storage_error_is_quorum_tolerated():
    # the retrying transport's NetworkStorageError subclasses
    # DiskNotFound: a wire blip is a gone drive to quorum logic
    assert isinstance(serr.NetworkStorageError("reset"), serr.DiskNotFound)
    population = errs((None, 4), (serr.NetworkStorageError, 2))
    assert meta.reduce_write_quorum_errs(population, IGN, 4) is None
