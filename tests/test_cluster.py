"""Multi-node cluster: remote drives inside ErasureSets, dsync NSLock,
bootstrap verify, node-loss reads, heal through remote shards.

The reference proves this with verify-build.sh's distributed matrix and
buildscripts/verify-healing.sh (3-node cluster, drive wipe + heal); here
four nodes run in one process on loopback ports — same RPC planes, no
containers.
"""

import socket
import threading
import time

import pytest

from minio_tpu.cluster import ClusterNode, NodeSpec
from minio_tpu.s3.credentials import Credentials
from minio_tpu.utils import ellipses

CREDS = Credentials(access_key="clusterkey", secret_key="clustersecret")


def _wait_remotes_online(nodes, timeout=30.0):
    """After a node restart, wait for every peer's transport probe to
    re-admit it (1 s probe interval; generous timeout for the 1-core CI
    host where the whole suite competes for the clock)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(rc.is_online()
               for n in nodes for rc in n._remote_clients):
            return
        time.sleep(0.2)
    raise AssertionError("remote drives did not come back online")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _boot_cluster(tmp_path, n_nodes=4, drives_per_node=4, parity=4,
                  set_drive_count=16):
    ports = _free_ports(n_nodes)
    nodes = []
    for i in range(n_nodes):
        drives = [str(tmp_path / f"n{i}d{j}")
                  for j in range(drives_per_node)]
        nodes.append(NodeSpec("127.0.0.1", ports[i], drives))

    out: list = [None] * n_nodes
    errs: list = [None] * n_nodes

    def boot(i):
        try:
            out[i] = ClusterNode(nodes, i, CREDS, parity=parity,
                                 set_drive_count=set_drive_count,
                                 block_size=1 << 16,
                                 format_timeout=60.0)
        except Exception as e:  # noqa: BLE001 — surfaced by the test
            errs[i] = e

    threads = [threading.Thread(target=boot, args=(i,))
               for i in range(n_nodes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for e in errs:
        if e is not None:
            raise e
    assert all(o is not None for o in out)
    return out


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cluster")
    nodes = _boot_cluster(tmp)
    yield nodes
    for n in nodes:
        try:
            n.shutdown()
        except Exception:  # noqa: BLE001
            pass


def test_cluster_boot_topology(cluster):
    for n in cluster:
        assert n.set_count == 1
        assert n.set_drive_count == 16
        # same deployment id everywhere
        assert n.sets.deployment_id == cluster[0].sets.deployment_id
    info = cluster[0].object_layer.storage_info()
    assert info["online_disks"] == 16


def test_put_on_one_node_get_on_another(cluster):
    a, b = cluster[0], cluster[3]
    a.object_layer.make_bucket("shared")
    payload = b"\xab" * 200_000  # multiple blocks at 64 KiB block size
    a.object_layer.put_object("shared", "obj1", payload)
    oi = b.object_layer.get_object_info("shared", "obj1")
    assert oi.size == len(payload)
    _, stream = b.object_layer.get_object("shared", "obj1")
    assert b"".join(stream) == payload


def test_get_survives_node_loss_and_heals(cluster):
    a, c = cluster[0], cluster[2]
    a.object_layer.make_bucket("lossy")
    payload = bytes(range(256)) * 1000
    a.object_layer.put_object("lossy", "obj", payload)

    # kill node 1's HTTP server: its 4 drives go dark (12 of 16 left,
    # exactly k for EC 12+4)
    victim = cluster[1]
    victim.s3.stop()
    try:
        _, stream = c.object_layer.get_object("lossy", "obj")
        assert b"".join(stream) == payload
    finally:
        victim._start_server("us-east-1", None)

    # drives are back; heal rewrites anything the dead node missed
    _wait_remotes_online(cluster)
    res = c.object_layer.heal_object("lossy", "obj")
    _, stream = c.object_layer.get_object("lossy", "obj")
    assert b"".join(stream) == payload


def test_put_during_node_loss_then_heal(cluster):
    """PUT with a node down writes exactly write-quorum (12 of 16)
    shards; after the node returns, heal rebuilds its 4 shards, proven by
    reading with a DIFFERENT node down afterwards."""
    a, d = cluster[0], cluster[3]
    a.object_layer.make_bucket("wounded")
    victim = cluster[2]
    victim.s3.stop()
    payload = b"x" * 150_000
    try:
        # EC 12+4 write quorum is 12: succeeds on the 12 online drives
        a.object_layer.put_object("wounded", "obj", payload)
    finally:
        victim._start_server("us-east-1", None)
    _wait_remotes_online(cluster)
    d.object_layer.heal_object("wounded", "obj")

    # node 2's shards must now be real: lose node 1 instead and read
    other = cluster[1]
    other.s3.stop()
    try:
        _, stream = d.object_layer.get_object("wounded", "obj")
        assert b"".join(stream) == payload
    finally:
        other._start_server("us-east-1", None)


def test_dsync_exclusive_across_nodes(cluster):
    a, b = cluster[0], cluster[1]
    la = a.sets.sets[0].ns.new_lock("zz/obj")
    lb = b.sets.sets[0].ns.new_lock("zz/obj")
    assert la.get_lock(timeout=5.0)
    try:
        assert not lb.get_lock(timeout=0.8)
    finally:
        la.unlock()
    assert lb.get_lock(timeout=5.0)
    lb.unlock()


def test_bootstrap_verify_rejects_mismatched_creds(tmp_path):
    ports = _free_ports(2)
    nodes = [NodeSpec("127.0.0.1", ports[0],
                      [str(tmp_path / f"ad{j}") for j in range(4)]),
             NodeSpec("127.0.0.1", ports[1],
                      [str(tmp_path / f"bd{j}") for j in range(4)])]
    good = threading.Thread(
        target=lambda: _try_boot(nodes, 0, CREDS), daemon=True)
    good.start()
    bad_creds = Credentials(access_key="clusterkey", secret_key="WRONG")
    with pytest.raises(RuntimeError):
        ClusterNode(nodes, 1, bad_creds, parity=2, set_drive_count=8,
                    block_size=1 << 16, bootstrap_timeout=6.0,
                    format_timeout=10.0)


def _try_boot(nodes, i, creds):
    try:
        n = ClusterNode(nodes, i, creds, parity=2, set_drive_count=8,
                        block_size=1 << 16, bootstrap_timeout=20.0,
                        format_timeout=20.0)
        n.shutdown()
    except Exception:  # noqa: BLE001 — partner may never come up
        pass


def test_ellipses_expansion():
    assert ellipses.expand_arg("/d{1...4}") == ["/d1", "/d2", "/d3", "/d4"]
    assert ellipses.expand_arg("/d{01...03}") == ["/d01", "/d02", "/d03"]
    assert ellipses.expand_arg("h{1...2}/d{1...2}") == [
        "h1/d1", "h1/d2", "h2/d1", "h2/d2"]
    assert ellipses.divide_into_sets(16) == (1, 16)
    assert ellipses.divide_into_sets(32) == (2, 16)
    assert ellipses.divide_into_sets(4) == (1, 4)
    with pytest.raises(ValueError):
        ellipses.divide_into_sets(17)


def test_peer_plane_verbs(cluster):
    """storage-info / trace / bucket-usage travel the peer plane."""
    a = cluster[0]
    infos = a.notification.storage_info_all()
    assert all(isinstance(i, dict) and i.get("online_disks") == 16
               for i in infos)
    # generate traffic on node 1's S3 listener, then pull its trace ring
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", cluster[1].spec.port,
                                      timeout=10)
    conn.request("GET", "/minio/health/live")
    conn.getresponse().read()
    conn.close()
    # the trace entry is recorded asynchronously wrt the response —
    # poll briefly instead of racing it
    import time as _time
    deadline = _time.time() + 5
    while _time.time() < deadline:
        merged = a.notification.trace_all()
        if any(e.get("path") == "/minio/health/live" for e in merged):
            break
        _time.sleep(0.1)
    assert any(e.get("path") == "/minio/health/live" for e in merged)


def test_iam_delta_propagation_not_wholesale(cluster):
    """A single-user change travels as a per-entity delta (reference
    LoadUser/LoadPolicy peer verbs) — peers must NOT re-walk the whole
    IAM store per mutation (VERDICT r3 item 7)."""
    a, b = cluster[0], cluster[1]
    full_loads = {"n": 0}
    orig_load = b.iam.load

    def counting_load(*a_, **kw):
        full_loads["n"] += 1
        orig_load()

    # intercept the RPC-reload hook itself: ClusterNode captured the
    # bound method at boot, so patching b.iam.load alone would miss
    # wholesale reload-iam verbs (and the periodic refresh thread calls
    # b.iam.load by attribute, which must NOT count here)
    orig_hook = b._peer_rpc.reload_iam
    b._peer_rpc.reload_iam = counting_load
    try:
        a.iam.add_user("deltauser", "deltasecret1")
        a.iam.attach_policy("readonly", user="deltauser")
        # peer B resolves the new user + mapping without a full reload
        cred = b.iam.get_credentials("deltauser")
        assert cred is not None and cred.secret_key == "deltasecret1"
        assert b.iam.user_policy.get("deltauser") == ["readonly"]
        assert full_loads["n"] == 0

        a.iam.set_user_status("deltauser", "off")
        assert b.iam.get_credentials("deltauser").status == "off"
        a.iam.remove_user("deltauser")
        assert b.iam.get_credentials("deltauser") is None
        assert b.iam.user_policy.get("deltauser") is None
        assert full_loads["n"] == 0

        # policy document deltas
        import json as _json
        from minio_tpu.iam.policy import Policy
        a.iam.set_policy("deltapol", Policy.from_json(_json.dumps({
            "Statement": [{"Effect": "Allow", "Action": "s3:GetObject",
                           "Resource": "*"}]})))
        assert "deltapol" in b.iam.policies
        a.iam.delete_policy("deltapol")
        assert "deltapol" not in b.iam.policies
        assert full_loads["n"] == 0
    finally:
        b._peer_rpc.reload_iam = orig_hook


def test_obd_net_probe(cluster):
    """Internode net perf probes (cmd/obdinfo.go): every peer reports
    throughput + RTT from the probing node's viewpoint."""
    a = cluster[0]
    net = a.notification.net_obd(size=1 << 18)
    assert len(net) == len(a.notification.peers)
    for r in net:
        assert "peer" in r
        assert r.get("throughput_mib_s", 0) > 0, r
        assert r.get("rtt_us", -1) >= 0
        assert r.get("bytes") == 1 << 18


def test_storage_class_parity(cluster):
    """REDUCED_REDUNDANCY storage class lowers parity per object via the
    config storage_class subsystem."""
    a = cluster[0]
    a.config.set_kv("storage_class", rrs="EC:2")
    assert a.s3.api._parity_for("REDUCED_REDUNDANCY") == 2
    assert a.s3.api._parity_for("STANDARD") is None   # no override set
    a.object_layer.make_bucket("scb")
    from minio_tpu.object.engine import PutOptions
    a.object_layer.put_object("scb", "rr", b"q" * 50_000,
                              opts=PutOptions(parity=2))
    info = a.object_layer.get_object_info("scb", "rr")
    assert info.parity_blocks == 2 and info.data_blocks == 14
    _, stream = a.object_layer.get_object("scb", "rr")
    assert b"".join(stream) == b"q" * 50_000


def test_cluster_profiling_console_obd(cluster):
    """Profiling fan-out, console-log merge, and OBD travel the peer
    plane (VERDICT r2 item 8). In-process nodes share one process-
    global profiler/console singleton, so counts are not per-node here
    — the assertions pin verb plumbing + payload shapes."""
    a = cluster[0]
    # profiling: start broadcasts; stop gathers at least one profile
    from minio_tpu.utils import profiling as prof_mod
    res = a.notification.profiling_start_all("cpu,mem")
    assert all(isinstance(r, dict) for r in res)
    assert prof_mod.running("cpu") and prof_mod.running("mem")
    stops = a.notification.profiling_stop_all("cpu,mem")
    assert any(isinstance(r, dict)
               and r.get("profiles", {}).get("cpu") for r in stops)
    # the mem kind returns a tracemalloc allocation-site report
    assert any("allocation sites" in
               (r.get("profiles", {}).get("mem") or "")
               for r in stops if isinstance(r, dict))
    assert not prof_mod.running("cpu") and not prof_mod.running("mem")

    # console log: a line logged on this process is visible via the
    # peer plane, with node attribution and time ordering
    from minio_tpu.utils.console import get_console
    get_console().log_line("INFO", "hello-from-test")
    merged = a.notification.console_log_all()
    assert any(e.get("message") == "hello-from-test" for e in merged)
    assert all("ts" in e and "node" in e for e in merged)

    # OBD: every PEER answers with cpu/mem facts and per-drive probes
    # (the notification list excludes the calling node itself)
    bundles = a.notification.obd_all()
    assert len(bundles) == len(cluster) - 1
    for b in bundles:
        assert b["cpu"]["count"] >= 1 and b["mem"]["total"] > 0
        assert len(b["drives"]) == 4        # drives_per_node
        assert all(d.get("ok") for d in b["drives"])
        assert all(d.get("write_latency_us", 0) > 0
                   for d in b["drives"])


def test_cluster_admin_profiling_zip_and_obd_endpoint(cluster):
    """The admin endpoints aggregate the peer plane: profiling/stop
    returns a zip, obdinfo and consolelog return per-node payloads —
    exercised through the madmin SDK."""
    from minio_tpu.madmin import AdminClient
    a = cluster[0]
    mc = AdminClient("127.0.0.1", a.spec.port, CREDS.access_key,
                     CREDS.secret_key)
    started = mc.profiling_start("cpu,mem")["kinds"]
    assert started["cpu"] in ("started", "already running")
    assert started["mem"] in ("started", "already running")
    mc.server_info()                      # some work to profile
    profiles = mc.profiling_stop("cpu,mem")
    assert profiles
    kinds = {n.split("-")[1] for n in profiles}
    assert kinds == {"cpu", "mem"}        # both kinds per node
    assert any("cumulative" in t for n, t in profiles.items()
               if n.startswith("profile-cpu-"))
    assert any("allocation sites" in t for n, t in profiles.items()
               if n.startswith("profile-mem-"))
    # unknown kind is a clean admin error
    import pytest as _pytest
    from minio_tpu.madmin import AdminClientError
    with _pytest.raises(AdminClientError):
        mc.profiling_start("block")

    nodes = mc.obd_info()
    assert len(nodes) == len(cluster)
    logs = mc.console_log()
    assert any("online" in e.get("message", "") for e in logs)
