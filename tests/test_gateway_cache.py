"""Gateways (NAS, S3-proxy) + disk cache wrapper (reference
cmd/gateway/{nas,s3} and cmd/disk-cache test intents)."""

from __future__ import annotations

import os

import pytest

from minio_tpu.gateway import new_gateway
from minio_tpu.object import api_errors
from minio_tpu.object.cache import CacheObjects
from minio_tpu.object.fs import FSObjects
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("gwtestkey123", "gwtestsecret123")


def test_nas_gateway_is_fs(tmp_path):
    gw = new_gateway("nas", path=str(tmp_path / "mount"))
    gw.make_bucket("share")
    gw.put_object("share", "doc.txt", b"on the nas")
    # the file is on the "mount" as a plain file
    assert open(tmp_path / "mount" / "share" / "doc.txt",
                "rb").read() == b"on the nas"
    _, stream = gw.get_object("share", "doc.txt")
    assert b"".join(stream) == b"on the nas"


def test_unknown_gateway():
    with pytest.raises(ValueError):
        new_gateway("gcsish")


@pytest.fixture()
def upstream(tmp_path):
    """A live 'remote cloud' S3 endpoint backed by an erasure set."""
    drives = [str(tmp_path / f"up{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS).start()
    yield srv
    srv.stop()
    sets.close()


def test_s3_gateway_proxies_objects(upstream, tmp_path):
    gw = new_gateway("s3", host="127.0.0.1", port=upstream.port,
                     access_key=CREDS.access_key,
                     secret_key=CREDS.secret_key)
    gw.make_bucket("remote")
    assert gw.bucket_exists("remote")
    assert "remote" in [v.name for v in gw.list_buckets()]

    payload = os.urandom(100_000)
    info = gw.put_object("remote", "obj", payload,
                         opts=__import__(
                             "minio_tpu.object.engine",
                             fromlist=["PutOptions"]).PutOptions(
                             metadata={"content-type": "application/x-t",
                                       "X-Amz-Meta-K": "v"}))
    assert info.etag

    got = gw.get_object_info("remote", "obj")
    assert got.size == len(payload)
    assert got.content_type == "application/x-t"
    assert got.user_defined.get("x-amz-meta-k") == "v"

    _, stream = gw.get_object("remote", "obj")
    assert b"".join(stream) == payload
    _, stream = gw.get_object("remote", "obj", offset=10, length=100)
    assert b"".join(stream) == payload[10:110]

    objs, _, _ = gw.list_objects("remote", prefix="ob")
    assert [o.name for o in objs] == ["obj"]

    gw.delete_object("remote", "obj")
    with pytest.raises(api_errors.ObjectApiError):
        gw.get_object_info("remote", "obj")


def test_s3_gateway_multipart(upstream):
    gw = new_gateway("s3", host="127.0.0.1", port=upstream.port,
                     access_key=CREDS.access_key,
                     secret_key=CREDS.secret_key)
    gw.make_bucket("mpb")
    uid = gw.new_multipart_upload("mpb", "big")
    from minio_tpu.object.multipart import CompletePart
    p1 = gw.put_object_part("mpb", "big", uid, 1, b"a" * 1000)
    p2 = gw.put_object_part("mpb", "big", uid, 2, b"b" * 1000)
    gw.complete_multipart_upload(
        "mpb", "big", uid,
        [CompletePart(1, p1.etag), CompletePart(2, p2.etag)])
    _, stream = gw.get_object("mpb", "big")
    assert b"".join(stream) == b"a" * 1000 + b"b" * 1000


def test_disk_cache_hits_and_invalidation(tmp_path):
    fs = FSObjects(str(tmp_path / "origin"))
    cache = CacheObjects(fs, str(tmp_path / "cache"),
                         budget_bytes=1 << 20)
    fs.make_bucket("cb")
    cache.put_object("cb", "o", b"version one")

    _, s = cache.get_object("cb", "o")
    assert b"".join(s) == b"version one"
    assert cache.misses == 1 and cache.hits == 0
    _, s = cache.get_object("cb", "o")
    assert b"".join(s) == b"version one"
    assert cache.hits == 1

    # overwrite via the CACHE wrapper invalidates
    cache.put_object("cb", "o", b"version two!")
    _, s = cache.get_object("cb", "o")
    assert b"".join(s) == b"version two!"

    # write BEHIND the cache (etag changes): stale entry is bypassed
    fs.put_object("cb", "o", b"behind the back")
    _, s = cache.get_object("cb", "o")
    assert b"".join(s) == b"behind the back"

    # ranged reads work from the cached entry
    _, s = cache.get_object("cb", "o", offset=7, length=3)
    assert b"".join(s) == b"the"


def test_disk_cache_detects_corruption(tmp_path):
    fs = FSObjects(str(tmp_path / "o2"))
    cache = CacheObjects(fs, str(tmp_path / "c2"))
    fs.make_bucket("b")
    cache.put_object("b", "k", b"pristine data")
    b"".join(cache.get_object("b", "k")[1])        # populate

    # flip a byte in the cached copy
    d = cache._entry_dir("b", "k")
    with open(os.path.join(d, "data"), "r+b") as f:
        f.seek(0)
        f.write(b"X")
    _, s = cache.get_object("b", "k")
    assert b"".join(s) == b"pristine data"          # served from origin


def test_disk_cache_purges_lru(tmp_path):
    fs = FSObjects(str(tmp_path / "o3"))
    cache = CacheObjects(fs, str(tmp_path / "c3"),
                         budget_bytes=100_000)
    fs.make_bucket("b")
    import time as _t
    for i in range(20):
        cache.put_object("b", f"k{i}", bytes(8000))
        b"".join(cache.get_object("b", f"k{i}")[1])
        _t.sleep(0.01)
    assert cache._usage() <= 100_000 * 0.95