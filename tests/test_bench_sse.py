"""CI smoke for bench.py --ab-sse: the encrypted data-path A/B must
run end-to-end inside the tier-1 budget, emit JSON-serializable
results with both passes at every concurrency point, show the device
pass actually dispatching (and coalescing the 2-stream point into
shared launches), and collect the dispatch-stage attribution."""

from __future__ import annotations

import json

import bench


def test_sse_ab_smoke():
    out = bench.bench_sse_ab(streams=(1, 2), size=1 << 18, objects=2,
                             drives=6, parity=2, block=1 << 16)
    json.dumps(out)                       # BENCH-compatible payload
    for mode in ("cpu", "device"):
        assert [p["streams"] for p in out[mode]] == [1, 2]
        for p in out[mode]:
            # byte-identity vs the plaintext is asserted INSIDE the
            # bench workers; here the rates just have to be real
            assert p["put_gib_s"] > 0 and p["get_gib_s"] > 0
    # the CPU pass never reaches the device (declined submissions
    # resolve to an already-done None future, no dispatch counted)
    assert all(p["launches"] == 0 for p in out["cpu"])
    # the device pass dispatched, and the 2-stream point coalesced
    # concurrent different-key encrypted PUTs into shared launches
    dev2 = out["device"][-1]
    assert dev2["launches"] >= 1
    assert dev2["coalesced"] >= 1
    assert out["put_speedup_x"] > 0 and out["get_speedup_x"] > 0
    # compressed+encrypted point ran both modes: plaintext-rate GiB/s
    # positive and the compressible payload actually shrank (the
    # engine ciphered the COMPRESSOR'S output, byte-checked back
    # through decrypt+decompress inside the bench)
    for mode in ("cpu_compressed", "device_compressed"):
        assert out[mode]["put_gib_s"] > 0
        assert out[mode]["get_gib_s"] > 0
        assert out[mode]["ratio"] > 1
    # queue/transfer/compute/fetch attribution was collected for the
    # fused encode dispatches
    stages = out["dispatch_stage_seconds"]
    assert any("compute" in k for k in stages)
