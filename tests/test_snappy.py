"""S2-interop compression (VERDICT r4 #2): snappy block + framing
codec golden vectors, native/pure-python cross-checks, and the live
server writing reference-readable compressed objects with zstd behind
config.

The hand-built vectors below are derived from the PUBLIC snappy
format descriptions (format_description.txt + framing_format.txt):
any compliant implementation — including the reference's s2.NewReader
— produces/accepts exactly these bytes.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import random
import struct
import urllib.parse

import pytest

from minio_tpu.features import crypto as sse
from minio_tpu.features import snappy as sn
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("snaptestkey1", "snaptestsecret1")
REGION = "us-east-1"


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

def test_crc32c_known_vectors():
    # RFC 3720 test vector
    assert sn.crc32c(b"123456789") == 0xE3069283
    assert sn._crc32c_py(b"123456789") == 0xE3069283
    assert sn.crc32c(b"") == 0
    # 32 zero bytes (iSCSI vector)
    assert sn.crc32c(bytes(32)) == 0x8A9136AA
    # native and python agree on arbitrary data
    data = os.urandom(1000)
    assert sn.crc32c(data) == sn._crc32c_py(data)


# ---------------------------------------------------------------------------
# block format
# ---------------------------------------------------------------------------

def test_block_golden_vector():
    """golang/snappy output for 30 x 'a': varint(30), 1-byte literal,
    copy2(offset 1, length 29) — any spec-compliant decoder reads it."""
    blob = bytes.fromhex("1e0061720100")
    assert sn.uncompress_block(blob) == b"a" * 30
    assert sn._uncompress_block_py(blob, 1 << 20) == b"a" * 30


def test_block_roundtrip_matrix():
    cases = [b"", b"a", b"ab" * 5, b"hello world " * 1000,
             os.urandom(65536), bytes(65536), os.urandom(17),
             b"x" * 65536, os.urandom(65535),
             (b"The quick brown fox. " * 4000)[:65536]]
    for data in cases:
        c = sn.compress_block(data)
        assert sn.uncompress_block(c) == data, len(data)
        # the pure-python decoder is an independent spec reading
        assert sn._uncompress_block_py(c, 1 << 24) == data, len(data)


def test_block_fuzz_roundtrip():
    rng = random.Random(7)
    for trial in range(100):
        n = rng.randrange(0, 65536)
        base = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 300)))
        data = (base * (n // max(len(base), 1) + 1))[:n]
        if rng.random() < 0.5:
            data = bytes(rng.randrange(256) for _ in range(n))
        c = sn.compress_block(data)
        assert sn.uncompress_block(c) == data, (trial, n)


def test_s2_repeat_offset_decode():
    """S2 extension: copy1 with offset 0 repeats the previous offset —
    'abcd' + copy(4,4) + repeat(4) = 'abcd'*3."""
    blk = bytes([12, 3 << 2]) + b"abcd" + bytes([1, 4]) + bytes([1, 0])
    want = b"abcd" * 3
    assert sn.uncompress_block(blk) == want
    assert sn._uncompress_block_py(blk, 1 << 20) == want


def test_s2_extended_repeat_refused_cleanly():
    # repeat (offset 0) with length code 5 -> extended form we refuse
    blk = bytes([12, 3 << 2]) + b"abcd" + bytes([1, 4]) + \
        bytes([(5 << 2) | 1, 0])
    with pytest.raises(NotImplementedError):
        sn.uncompress_block(blk)
    with pytest.raises(NotImplementedError):
        sn._uncompress_block_py(blk, 1 << 20)


def test_block_corruption_detected():
    with pytest.raises((ValueError, sn.SnappyError)):
        sn.uncompress_block(b"\xff\xff\xff\xff\xff\xff")   # bad varint
    # copy beyond output start
    with pytest.raises((ValueError, sn.SnappyError)):
        sn.uncompress_block(bytes([4, 0 << 2]) + b"a" + bytes([1, 9]))


# ---------------------------------------------------------------------------
# framing format
# ---------------------------------------------------------------------------

def _frame_uncompressed(data: bytes) -> bytes:
    body = struct.pack("<I", sn.masked_crc(data)) + data
    return bytes([0x01]) + len(body).to_bytes(3, "little") + body


def test_framing_golden_handbuilt():
    """Hand-built per framing_format.txt: ident + one uncompressed
    chunk; also with padding and skippable chunks interleaved."""
    hand = sn.STREAM_IDENT + _frame_uncompressed(b"hello")
    assert b"".join(sn.decompress_stream(iter([hand]))) == b"hello"

    blk = bytes([5, 4 << 2]) + b"hello"          # literal block
    comp = sn.STREAM_IDENT + bytes([0x00]) + \
        (4 + len(blk)).to_bytes(3, "little") + \
        struct.pack("<I", sn.masked_crc(b"hello")) + blk
    assert b"".join(sn.decompress_stream(iter([comp]))) == b"hello"

    # padding (0xfe) and skippable (0x80) chunks are transparent
    pad = bytes([0xfe]) + (3).to_bytes(3, "little") + b"\0\0\0"
    skip = bytes([0x80]) + (2).to_bytes(3, "little") + b"zz"
    mixed = sn.STREAM_IDENT + pad + _frame_uncompressed(b"ab") + skip \
        + _frame_uncompressed(b"cd")
    assert b"".join(sn.decompress_stream(iter([mixed]))) == b"abcd"


def test_framing_accepts_s2_writer_magic():
    """The reference's s2.NewWriter stamps \\xff 06 00 00 'S2sTwO';
    chunk layout is otherwise identical — a reference-written stream
    of snappy-subset blocks must decode."""
    s2_ident = b"\xff\x06\x00\x00" + sn.S2_IDENT_BODY
    stream = s2_ident + _frame_uncompressed(b"from-the-reference")
    got = b"".join(sn.decompress_stream(iter([stream])))
    assert got == b"from-the-reference"


def test_legacy_capital_c_metadata_key_still_reads():
    """Objects written by the pre-r5 build carry
    X-Minio-Internal-Compression (capital C) = zstd; reads must keep
    decoding them after the key/default change."""
    payload = b"old-object " * 400
    z = sse.ZstdCompress()
    blob = z.update(payload) + z.finalize()
    md = {sse.MK_COMPRESS_LEGACY: "zstd"}
    assert sse.stored_compression(md) == "zstd"
    got = b"".join(sse.decompress_stream(
        iter([blob]), sse.stored_compression(md)))
    assert got == payload


def test_framing_error_modes():
    good = _frame_uncompressed(b"hello")
    # missing stream identifier
    with pytest.raises(sn.SnappyError):
        list(sn.decompress_stream(iter([good])))
    # corrupt CRC
    bad = bytearray(sn.STREAM_IDENT + good)
    bad[-1] ^= 1
    with pytest.raises(sn.SnappyError):
        list(sn.decompress_stream(iter([bytes(bad)])))
    # reserved unskippable chunk
    res = sn.STREAM_IDENT + bytes([0x02]) + (1).to_bytes(3, "little") \
        + b"x"
    with pytest.raises(sn.SnappyError):
        list(sn.decompress_stream(iter([res])))
    # truncated frame
    with pytest.raises(sn.SnappyError):
        list(sn.decompress_stream(iter([sn.STREAM_IDENT + good[:-2]])))


def test_framing_roundtrip_chunked():
    payload = (b"The quick brown fox jumps. " * 10000) + \
        os.urandom(150000)
    t = sn.SnappyFramedCompress()
    framed = t.update(payload[:77]) + t.update(payload[77:]) + \
        t.finalize()
    assert framed.startswith(sn.STREAM_IDENT)
    # arbitrary re-chunking on the read side
    pieces = [framed[i:i + 7777] for i in range(0, len(framed), 7777)]
    assert b"".join(sn.decompress_stream(iter(pieces))) == payload
    # empty payload still emits a valid (ident-only) stream
    t2 = sn.SnappyFramedCompress()
    empty = t2.finalize()
    assert empty == sn.STREAM_IDENT
    assert b"".join(sn.decompress_stream(iter([empty]))) == b""


def test_crypto_dispatch_by_metadata_value():
    """crypto.decompress_stream picks the decoder from the stored
    MK_COMPRESS value: s2/v1 -> framing reader, zstd -> zstd."""
    payload = b"dispatch-me " * 5000
    t = sn.SnappyFramedCompress()
    framed = t.update(payload) + t.finalize()
    for algo in (sse.COMPRESS_S2, sse.COMPRESS_SNAPPY_V1):
        got = b"".join(sse.decompress_stream(iter([framed]), algo))
        assert got == payload, algo
    z = sse.ZstdCompress()
    zblob = z.update(payload) + z.finalize()
    got = b"".join(sse.decompress_stream(iter([zblob]),
                                         sse.COMPRESS_ZSTD))
    assert got == payload


# ---------------------------------------------------------------------------
# live server: interop-default compression, zstd behind config
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("snapdrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 17)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    srv.api.compression_enabled = True
    yield srv
    srv.stop()
    sets.close()


def _req(srv, method, path, body=b"", headers=None):
    hdrs = {k.lower(): v for k, v in (headers or {}).items()}
    hdrs["host"] = f"127.0.0.1:{srv.port}"
    ph = hashlib.sha256(body).hexdigest()
    hdrs = sig.sign_v4(method, urllib.parse.quote(path), {}, hdrs, ph,
                       CREDS, REGION)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    conn.request(method, urllib.parse.quote(path), body=body,
                 headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    out = {k.lower(): v for k, v in resp.getheaders()}
    conn.close()
    return resp.status, out, data


def test_server_writes_s2_interop_objects(server):
    srv = server
    assert _req(srv, "PUT", "/snapbkt")[0] == 200
    payload = (b"compress me please, I am very repetitive. " * 8000)
    st, _, _ = _req(srv, "PUT", "/snapbkt/doc.txt", body=payload,
                    headers={"content-type": "text/plain"})
    assert st == 200

    # stored form: reference metadata value + snappy framing magic,
    # i.e. byte-valid input for the reference's s2.NewReader
    info = srv.api.obj.get_object_info("snapbkt", "doc.txt")
    assert info.user_defined.get(sse.MK_COMPRESS) == sse.COMPRESS_S2
    assert info.size < len(payload)
    _, stream = srv.api.obj.get_object("snapbkt", "doc.txt", 0,
                                       len(sn.STREAM_IDENT))
    assert b"".join(stream) == sn.STREAM_IDENT

    # decodes through the framing reader on GET, full + ranged
    st, hdrs, data = _req(srv, "GET", "/snapbkt/doc.txt")
    assert st == 200 and data == payload
    assert hdrs["content-length"] == str(len(payload))
    st, _, data = _req(srv, "GET", "/snapbkt/doc.txt",
                       headers={"range": "bytes=100000-100099"})
    assert st == 206 and data == payload[100000:100100]


def test_server_zstd_behind_config(server):
    srv = server
    srv.api.compression_algorithm = "zstd"
    try:
        payload = b"zstd-configured object body " * 6000
        st, _, _ = _req(srv, "PUT", "/snapbkt/legacy.txt",
                        body=payload,
                        headers={"content-type": "text/plain"})
        assert st == 200
        info = srv.api.obj.get_object_info("snapbkt", "legacy.txt")
        assert info.user_defined.get(sse.MK_COMPRESS) == \
            sse.COMPRESS_ZSTD
    finally:
        srv.api.compression_algorithm = "s2"
    # both algorithms readable side by side (old r4 objects keep
    # decoding after the default flip)
    st, _, data = _req(srv, "GET", "/snapbkt/legacy.txt")
    assert st == 200 and data == payload
    st, _, data = _req(srv, "GET", "/snapbkt/doc.txt")
    assert st == 200


def test_server_reads_v1_snappy_objects(server):
    """An object tagged with the v1 value (golang/snappy framed stream
    — byte-identical framing) reads back decoded."""
    srv = server
    payload = b"v1-compressed object " * 3000
    t = sn.SnappyFramedCompress()
    framed = t.update(payload) + t.finalize()
    from minio_tpu.object.engine import PutOptions
    srv.api.obj.put_object(
        "snapbkt", "v1obj.txt", framed, len(framed),
        PutOptions(metadata={
            "etag": hashlib.md5(payload).hexdigest(),
            sse.MK_COMPRESS: sse.COMPRESS_SNAPPY_V1,
            sse.MK_ACTUAL: str(len(payload))}))
    st, _, data = _req(srv, "GET", "/snapbkt/v1obj.txt")
    assert st == 200 and data == payload


def test_server_reads_pre_r5_legacy_key_objects(server):
    """e2e: an on-disk object whose metadata carries the old capital-C
    key serves decoded through the GET path."""
    srv = server
    payload = b"pre-r5 stored object " * 2500
    z = sse.ZstdCompress()
    blob = z.update(payload) + z.finalize()
    from minio_tpu.object.engine import PutOptions
    srv.api.obj.put_object(
        "snapbkt", "old.txt", blob, len(blob),
        PutOptions(metadata={
            "etag": hashlib.md5(payload).hexdigest(),
            sse.MK_COMPRESS_LEGACY: "zstd",
            sse.MK_ACTUAL: str(len(payload))}))
    st, hdrs, data = _req(srv, "GET", "/snapbkt/old.txt")
    assert st == 200 and data == payload
    assert hdrs["content-length"] == str(len(payload))


def test_compression_algorithm_config_kv(tmp_path):
    from minio_tpu.config.kv import ConfigSys

    class _API:
        region = "us-east-1"
        cors_allow_origin = "*"
        compression_enabled = False
        compression_algorithm = "s2"
        kms = None

        @staticmethod
        def set_max_clients(n):
            pass

    sets = ErasureSets.from_drives(
        [str(tmp_path / f"cfg{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    try:
        cfg = ConfigSys(sets)
        assert cfg.get("compression", "algorithm") == "s2"
        cfg.set_kv("compression", enable="on", algorithm="zstd")
        api = _API()
        cfg.apply(api)
        assert api.compression_enabled
        assert api.compression_algorithm == "zstd"
    finally:
        sets.close()
