"""CI smoke for bench.py --ab-gray: the gray-failure A/B must run
end-to-end inside the tier-1 budget, emit a JSON-serializable payload,
and prove the plane's three claims at smoke scale — GET p99 improves
with hedging on, PUT acks at quorum below the injected stall, zero
acked-write loss after the MRF drain, and the stalled drive completes
the quarantine → probation → re-admission round trip."""

from __future__ import annotations

import json

import pytest

import bench

pytestmark = pytest.mark.chaos


def test_gray_ab_smoke():
    out = bench.bench_gray_ab(objects=5, size=1 << 18, gets=20,
                              streams=4, drives=6, block=1 << 16,
                              stall_s=0.3)
    json.dumps(out)                     # BENCH-compatible payload
    assert out["config"]["stall_s"] == 0.3
    # the injector really fired in BOTH passes
    assert out["off"]["stalls_injected"] > 0
    assert out["on"]["stalls_injected"] > 0
    # tail latency: the full bench shows >= 3x at 0.5 s stalls; at
    # smoke scale on a loaded CI box we pin a clear win, not the bar
    assert out["get_p99_speedup_x"] > 2.0, out
    assert out["put_p99_speedup_x"] > 2.0, out
    # PUT acks at quorum: the stalled drive no longer binds p99
    assert out["put_p99_below_stall"] is True
    assert out["on"]["put"]["p99_ms"] < 300.0
    # zero acked-write loss once MRF drains (asserted in-bench too)
    assert out["lost_after_mrf"] == 0
    # quarantine round trip: convicted while slow, re-admitted after
    # probation probes + heal verify once the stall cleared
    states = out["quarantine"]["states"]
    assert states[0] == "suspect" and states[-1] == "ok"
    events = [e for _k, e in out["quarantine"]["events"]]
    assert events[0] == "suspect" and "probation" in events \
        and events[-1] == "readmit"
