"""Bucket event notification plane (minio_tpu/notify/).

The acceptance battery: the reference S3 event record shape is pinned
per mutation verb (PUT, multipart complete, delete marker, version
purge, transition, restore) with its exact key sets including the
``responseElements`` origin metadata; NotificationConfiguration XML
parses with prefix/suffix/event filters and rejects rules that can
never fire; the epoch-versioned target registry persists to every
pool, recovers deterministically, and rolls back a failed save; the
chaos tier (NaughtyTarget 503 storms / offline windows / mid-POST
death) loses ZERO events through the durable per-target queue + MRF
retry; a restart replays the persisted backlog; replica applies are
suppressed by default; and on multi-node membership only the bucket's
rendezvous owner delivers (with local fallback when the owner is
unreachable — a duplicate beats a lost event).
"""

from __future__ import annotations

import io
import json
import time

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions
from minio_tpu.object.multipart import CompletePart
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.notify import (BucketNotifyConfig, NaughtyTarget,
                              NotificationPlane, NotifyRuleError,
                              NotifyTarget, NotifyTargetError,
                              NotifyTargetRegistry, new_arn)
from minio_tpu.notify.plane import _owner_of, render_record
from minio_tpu.notify.targets import QueueSender
from minio_tpu.replicate.targets import REPL_ORIGIN_KEY
from minio_tpu.utils.streams import IterStream

ALL_EVENTS = ("s3:ObjectCreated:*", "s3:ObjectRemoved:*",
              "s3:ObjectRestore:*", "s3:ObjectTransition:*")


def _xml(arn, events=ALL_EVENTS, prefix="", suffix=""):
    ev = "".join(f"<Event>{e}</Event>" for e in events)
    flt = ""
    rules = ""
    if prefix:
        rules += ("<FilterRule><Name>prefix</Name>"
                  f"<Value>{prefix}</Value></FilterRule>")
    if suffix:
        rules += ("<FilterRule><Name>suffix</Name>"
                  f"<Value>{suffix}</Value></FilterRule>")
    if rules:
        flt = f"<Filter><S3Key>{rules}</S3Key></Filter>"
    return ("<NotificationConfiguration><QueueConfiguration>"
            f"<Queue>{arn}</Queue>{ev}{flt}"
            "</QueueConfiguration></NotificationConfiguration>")


def _mk_layer(root, buckets=("b",), drives=4):
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(drives)],
        set_count=1, set_drive_count=drives, parity=2,
        block_size=1 << 16)
    layer = ErasureServerSets([sets], load_topology=False)
    for b in buckets:
        layer.make_bucket(b)
    return layer


def _mk_plane(layer, queue_dir=None, **kw):
    reg = NotifyTargetRegistry(layer)
    arn = new_arn("t", "queue")
    reg.add(NotifyTarget(arn=arn, type="queue"))
    sink = QueueSender(arn)
    reg.set_sender(arn, sink)
    plane = NotificationPlane(layer, reg, queue_dir=queue_dir,
                              busy_fn=lambda: False, **kw)
    plane.set_config("b", _xml(arn))
    layer.attach_notifications(plane)
    return plane, reg, arn, sink


def _drain(plane, sink, n, timeout=30.0):
    assert plane.drain(timeout), plane.stats()
    assert sink.wait_for(n, timeout), (len(sink.records), plane.stats())


# ---------------------------------------------------------------------------
# record schema: one pinned shape per mutation verb
# ---------------------------------------------------------------------------

RECORD_KEYS = {"eventVersion", "eventSource", "awsRegion", "eventTime",
               "eventName", "userIdentity", "requestParameters",
               "responseElements", "s3"}
RESPONSE_KEYS = {"x-amz-request-id", "x-minio-origin-node",
                 "x-minio-origin-site", "x-minio-tier"}
S3_KEYS = {"s3SchemaVersion", "configurationId", "bucket", "object"}
OBJECT_KEYS = {"key", "size", "eTag", "versionId", "sequencer"}


def _assert_shape(record, event_name, bucket="b", key=None):
    assert set(record) == {"Records"} and len(record["Records"]) == 1
    rec = record["Records"][0]
    assert set(rec) == RECORD_KEYS
    assert rec["eventVersion"] == "2.0"
    assert rec["eventSource"] == "minio:s3"
    assert rec["eventName"] == event_name
    assert set(rec["responseElements"]) == RESPONSE_KEYS
    assert set(rec["s3"]) == S3_KEYS
    assert rec["s3"]["s3SchemaVersion"] == "1.0"
    assert rec["s3"]["bucket"]["arn"] == f"arn:aws:s3:::{bucket}"
    obj = rec["s3"]["object"]
    assert set(obj) == OBJECT_KEYS
    if key is not None:
        assert obj["key"] == key
    # the record is a pure JSON document (webhook-POSTable bytes)
    json.dumps(record)
    return rec


def test_record_shape_put_and_multipart(tmp_path):
    """PUT fires s3:ObjectCreated:Put; a multipart commit fires
    s3:ObjectCreated:CompleteMultipartUpload carrying the multipart
    etag — each with the full reference key set."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer, node="n1:9000")

    info = layer.put_object("b", "dir/a.txt", b"x" * 64,
                            opts=PutOptions(versioned=True))
    _drain(plane, sink, 1)
    rec = _assert_shape(sink.records[0], "s3:ObjectCreated:Put",
                        key="dir/a.txt")
    obj = rec["s3"]["object"]
    assert obj["size"] == 64
    assert obj["eTag"] == info.etag
    assert obj["versionId"] == info.version_id
    assert rec["responseElements"]["x-minio-origin-node"] == "n1:9000"

    p1, p2 = b"p" * (5 << 20), b"q" * (1 << 20)
    up = layer.new_multipart_upload("b", "mp", PutOptions())
    e1 = layer.put_object_part("b", "mp", up, 1,
                               io.BytesIO(p1), len(p1)).etag
    e2 = layer.put_object_part("b", "mp", up, 2,
                               io.BytesIO(p2), len(p2)).etag
    mi = layer.complete_multipart_upload(
        "b", "mp", up, [CompletePart(1, e1), CompletePart(2, e2)])
    _drain(plane, sink, 2)
    rec = _assert_shape(sink.records[1],
                        "s3:ObjectCreated:CompleteMultipartUpload",
                        key="mp")
    assert rec["s3"]["object"]["eTag"] == mi.etag
    assert rec["s3"]["object"]["eTag"].endswith("-2")
    plane.close()


def test_record_shape_delete_marker_and_purge(tmp_path):
    """A versioned delete fires DeleteMarkerCreated (carrying the
    marker's version id); purging the last version fires
    ObjectRemoved:Delete with the key gone."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer)

    layer.put_object("b", "doc", b"v1", opts=PutOptions(versioned=True))
    _drain(plane, sink, 1)
    layer.delete_object("b", "doc", versioned=True)
    _drain(plane, sink, 2)
    rec = _assert_shape(sink.records[1],
                        "s3:ObjectRemoved:DeleteMarkerCreated",
                        key="doc")
    assert rec["s3"]["object"]["versionId"]

    layer.put_object("b", "gone", b"x")
    _drain(plane, sink, 3)
    layer.delete_object("b", "gone")
    _drain(plane, sink, 4)
    rec = _assert_shape(sink.records[3], "s3:ObjectRemoved:Delete",
                        key="gone")
    assert rec["s3"]["object"]["size"] == 0
    assert rec["s3"]["object"]["eTag"] == ""
    plane.close()


def test_record_shape_transition_and_restore(tmp_path):
    """Tiering fires ObjectTransition:Complete with x-minio-tier
    naming the remote tier; a finished restore fires
    ObjectRestore:Completed (still carrying the tier)."""
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import restore_object

    layer = _mk_layer(tmp_path / "site")
    tiers = TierManager(layer)
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    plane, reg, arn, sink = _mk_plane(layer)

    layer.put_object("b", "arch", b"z" * 4096,
                     opts=PutOptions(versioned=True))
    _drain(plane, sink, 1)
    oi = layer.get_object_info("b", "arch")
    _, stream = layer.get_object("b", "arch")
    rd = IterStream(stream)
    rk = tiers.remote_key("b", "arch", oi.version_id)
    try:
        tiers.client("cold").put(rk, rd, oi.size)
    finally:
        rd.close()
    layer.transition_object("b", "arch", version_id=oi.version_id,
                            tier="cold", remote_object=rk,
                            expect_etag=oi.etag)
    _drain(plane, sink, 2)
    rec = _assert_shape(sink.records[1], "s3:ObjectTransition:Complete",
                        key="arch")
    assert rec["responseElements"]["x-minio-tier"] == "cold"

    restore_object(layer, tiers, "b", "arch", version_id=oi.version_id)
    _drain(plane, sink, 3)
    rec = _assert_shape(sink.records[2], "s3:ObjectRestore:Completed",
                        key="arch")
    assert rec["responseElements"]["x-minio-tier"] == "cold"
    plane.close()


def test_record_origin_site_and_replica_suppression(tmp_path):
    """A replica apply (REPL_ORIGIN_KEY metadata) fires NO event by
    default; with replica events on, the record's responseElements
    carries the ORIGIN site id — never the local one."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer, site_id="siteB")

    layer.put_object("b", "replica", b"x",
                     opts=PutOptions(metadata={REPL_ORIGIN_KEY: "siteA"}))
    assert plane.drain(30), plane.stats()
    assert sink.records == []
    assert plane.stats()["suppressed"] == 1

    plane.replica_events = True
    plane.on_namespace_change("b", "replica")
    _drain(plane, sink, 1)
    rec = _assert_shape(sink.records[0], "s3:ObjectCreated:Put",
                        key="replica")
    assert rec["responseElements"]["x-minio-origin-site"] == "siteA"

    # a local write reports the local site as its origin
    layer.put_object("b", "local", b"y")
    _drain(plane, sink, 2)
    rec = _assert_shape(sink.records[1], "s3:ObjectCreated:Put",
                        key="local")
    assert rec["responseElements"]["x-minio-origin-site"] == "siteB"
    plane.close()


def test_render_record_key_is_url_encoded():
    rec = render_record("s3:ObjectCreated:Put", "b", "a b/c+d")
    assert rec["Records"][0]["s3"]["object"]["key"] == "a%20b/c%2Bd"


# ---------------------------------------------------------------------------
# rules: NotificationConfiguration parsing + filters
# ---------------------------------------------------------------------------

def test_rules_parse_filter_and_match():
    arn1, arn2 = new_arn("one", "queue"), new_arn("two", "webhook")
    xml = f"""<NotificationConfiguration>
      <QueueConfiguration>
        <Queue>{arn1}</Queue>
        <Event>s3:ObjectCreated:*</Event>
        <Filter><S3Key>
          <FilterRule><Name>prefix</Name><Value>img/</Value></FilterRule>
          <FilterRule><Name>suffix</Name><Value>.jpg</Value></FilterRule>
        </S3Key></Filter>
      </QueueConfiguration>
      <TopicConfiguration>
        <Topic>{arn2}</Topic>
        <Event>s3:ObjectRemoved:Delete</Event>
      </TopicConfiguration>
    </NotificationConfiguration>"""
    cfg = BucketNotifyConfig.from_xml(xml)
    assert cfg.arns() == {arn1, arn2}
    assert cfg.match("s3:ObjectCreated:Put", "img/x.jpg") == {arn1}
    assert cfg.match("s3:ObjectCreated:Put", "img/x.png") == set()
    assert cfg.match("s3:ObjectCreated:Put", "doc/x.jpg") == set()
    assert cfg.match("s3:ObjectRemoved:Delete", "any") == {arn2}
    assert cfg.match("s3:ObjectRemoved:DeleteMarkerCreated",
                     "any") == set()
    assert cfg.unknown_events() == []


def test_rules_reject_malformed():
    with pytest.raises(NotifyRuleError):
        BucketNotifyConfig.from_xml("<not-xml")
    with pytest.raises(NotifyRuleError):       # entry without an ARN
        BucketNotifyConfig.from_xml(
            "<NotificationConfiguration><QueueConfiguration>"
            "<Event>s3:ObjectCreated:*</Event>"
            "</QueueConfiguration></NotificationConfiguration>")
    with pytest.raises(NotifyRuleError):       # rule without events
        BucketNotifyConfig.from_xml(
            "<NotificationConfiguration><QueueConfiguration>"
            "<Queue>arn:minio:sqs::x:queue</Queue>"
            "</QueueConfiguration></NotificationConfiguration>")
    cfg = BucketNotifyConfig.from_xml(_xml(
        "arn:minio:sqs::x:queue", events=("s3:ObjectTypo:*",)))
    assert cfg.unknown_events() == ["s3:ObjectTypo:*"]


# ---------------------------------------------------------------------------
# registry: epoch persistence, recovery, rollback
# ---------------------------------------------------------------------------

def test_registry_persists_recovers_and_redacts(tmp_path):
    layer = _mk_layer(tmp_path)
    reg = NotifyTargetRegistry(layer)
    arn = new_arn("hook", "webhook")
    reg.add(NotifyTarget(arn=arn, type="webhook",
                         params={"endpoint": "http://x/",
                                 "auth_token": "sekrit"}))
    reg.add(NotifyTarget(arn=new_arn("q", "queue"), type="queue"))
    assert reg.epoch == 2

    # secrets never leave the registry redacted surface
    listed = {t["arn"]: t for t in reg.list(redact=True)}
    assert listed[arn]["params"]["auth_token"] == "REDACTED"

    fresh = NotifyTargetRegistry(layer)
    assert fresh.load()
    assert fresh.epoch == 2
    assert fresh.arns() == reg.arns()
    assert fresh.lineage == reg.lineage
    # the persisted doc keeps the REAL secret (load must round-trip)
    assert fresh.get(arn).params["auth_token"] == "sekrit"

    fresh.remove(arn)
    assert fresh.epoch == 3
    again = NotifyTargetRegistry(layer)
    assert again.load() and again.epoch == 3
    assert arn not in again.arns()


def test_registry_rolls_back_on_failed_save(tmp_path):
    layer = _mk_layer(tmp_path)
    reg = NotifyTargetRegistry(layer)
    arn = new_arn("a", "queue")
    reg.add(NotifyTarget(arn=arn, type="queue"))

    def boom(*a, **kw):
        raise OSError("pool down")

    pools = list(layer.server_sets)
    saved = [p.put_object for p in pools]
    for p in pools:
        p.put_object = boom
    try:
        with pytest.raises(NotifyTargetError):
            reg.add(NotifyTarget(arn=new_arn("b", "queue"),
                                 type="queue"))
        with pytest.raises(NotifyTargetError):
            reg.remove(arn)
    finally:
        for p, fn in zip(pools, saved):
            p.put_object = fn
    # both mutations rolled back: the map still holds exactly `arn`
    assert reg.arns() == {arn}
    assert NotifyTargetRegistry(layer).load() is True


def test_registry_validates_specs():
    reg = NotifyTargetRegistry(None)
    with pytest.raises(NotifyTargetError):
        NotifyTarget.from_dict({"type": "webhook"})        # no arn
    with pytest.raises(NotifyTargetError):
        NotifyTarget.from_dict({"arn": "a", "type": "nats"})
    with pytest.raises(NotifyTargetError):                 # no endpoint
        reg.add(NotifyTarget(arn="a", type="webhook"))
    with pytest.raises(NotifyTargetError):
        reg.remove("missing")
    arn = new_arn("", "queue")
    assert arn.startswith("arn:minio:sqs::") and arn.endswith(":queue")
    reg.add(NotifyTarget(arn=arn, type="queue"))
    with pytest.raises(NotifyTargetError):                 # duplicate
        reg.add(NotifyTarget(arn=arn, type="queue"))
    reg.add(NotifyTarget(arn=arn, type="queue"), update=True)


# ---------------------------------------------------------------------------
# chaos: zero loss through storms, offline windows, mid-POST death
# ---------------------------------------------------------------------------

def test_chaos_503_storm_loses_nothing(tmp_path):
    """Every send fails for a while (a 503 storm): the durable queue
    holds the backlog and the MRF retry drains it clean — all events
    arrive exactly once."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer)
    naughty = NaughtyTarget(sink, fail_first=6)
    reg.set_sender(arn, naughty)

    for i in range(8):
        layer.put_object("b", f"storm/{i}", b"x")
    _drain(plane, sink, 8, timeout=60)
    keys = {r["Records"][0]["s3"]["object"]["key"]
            for r in sink.records}
    assert keys == {f"storm/{i}" for i in range(8)}
    assert len(sink.records) == 8              # no duplicates either
    assert naughty.failures >= 6
    assert plane.stats()["backlog"] == 0
    plane.close()


def test_chaos_offline_windows_lose_nothing(tmp_path):
    """Recurring offline windows (every 3rd send opens a 2-failure
    window): the offline gate parks the backlog, the redrive sweep
    reprobes, everything arrives."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer)
    reg.set_sender(arn, NaughtyTarget(sink, offline_every=(3, 2)))

    for i in range(12):
        layer.put_object("b", f"w/{i}", b"y")
    _drain(plane, sink, 12, timeout=60)
    keys = {r["Records"][0]["s3"]["object"]["key"]
            for r in sink.records}
    assert keys == {f"w/{i}" for i in range(12)}
    assert plane.stats()["backlog"] == 0
    plane.close()


def test_chaos_mid_post_death_duplicates_never_loses(tmp_path):
    """The n-th POST lands but the ack is lost: the plane must retry
    (the consumer sees a duplicate) — at-least-once, zero loss."""
    layer = _mk_layer(tmp_path)
    plane, reg, arn, sink = _mk_plane(layer)
    reg.set_sender(arn, NaughtyTarget(sink, die_after_send=3))

    for i in range(6):
        layer.put_object("b", f"dup/{i}", b"z")
    assert plane.drain(60), plane.stats()
    keys = {r["Records"][0]["s3"]["object"]["key"]
            for r in sink.records}
    assert keys == {f"dup/{i}" for i in range(6)}      # nothing lost
    assert len(sink.records) >= 6                      # dup allowed
    assert plane.stats()["backlog"] == 0
    plane.close()


def test_restart_replays_durable_backlog(tmp_path):
    """Kill/replay without the process harness: a dead target leaves
    its records in the on-disk queue; a NEW plane over the same queue
    directory redrives them at boot — zero loss across the restart."""
    layer = _mk_layer(tmp_path / "site")
    qdir = str(tmp_path / "queue")
    plane, reg, arn, sink = _mk_plane(layer, queue_dir=qdir)

    class Dead:
        def send(self, record):
            raise ConnectionError("down")

    reg.set_sender(arn, Dead())
    for i in range(5):
        layer.put_object("b", f"crash/{i}", b"x")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline \
            and plane.stats()["backlog"] < 5:
        time.sleep(0.02)
    assert plane.stats()["backlog"] == 5, plane.stats()
    plane.close()

    # "restart": fresh plane, same durable queue, target back up
    reg.set_sender(arn, sink)
    plane2 = NotificationPlane(layer, reg, queue_dir=qdir,
                               busy_fn=lambda: False)
    plane2.set_config("b", _xml(arn))
    _drain(plane2, sink, 5, timeout=60)
    keys = {r["Records"][0]["s3"]["object"]["key"]
            for r in sink.records}
    assert keys == {f"crash/{i}" for i in range(5)}
    assert plane2.stats()["backlog"] == 0
    plane2.close()


# ---------------------------------------------------------------------------
# ownership: one deliverer per bucket on multi-node membership
# ---------------------------------------------------------------------------

def test_owner_routing_forwards_and_falls_back(tmp_path):
    """A non-owner node FORWARDS the event to the bucket's rendezvous
    owner instead of delivering; when the owner is unreachable it
    delivers locally (duplicate beats loss). Peer-ingested events
    deliver without re-resolution."""
    layer = _mk_layer(tmp_path)
    nodes = ["10.0.0.1:9000", "10.0.0.2:9000"]
    owner = _owner_of("b", sorted(nodes))
    other = next(n for n in nodes if n != owner)

    reg = NotifyTargetRegistry(layer)
    arn = new_arn("t", "queue")
    reg.add(NotifyTarget(arn=arn, type="queue"))
    sink = QueueSender(arn)
    reg.set_sender(arn, sink)
    plane = NotificationPlane(layer, reg, node=other, nodes=nodes,
                              busy_fn=lambda: False)
    plane.set_config("b", _xml(arn))
    layer.attach_notifications(plane)
    assert plane.owner_of("b") == owner

    forwarded = []
    plane.forward_fn = lambda addr, b, k: (
        forwarded.append((addr, b, k)) or True)
    layer.put_object("b", "routed", b"x")
    assert plane.drain(30), plane.stats()
    assert forwarded == [(owner, "b", "routed")]
    assert sink.records == []                  # not delivered here
    assert plane.stats()["forwarded"] == 1

    # owner down: the forward fails and the event lands locally
    plane.forward_fn = lambda addr, b, k: False
    plane.on_namespace_change("b", "routed")
    _drain(plane, sink, 1)
    assert plane.stats()["fallback_local"] == 1

    # the owner side: ingest() delivers locally, no re-resolution
    plane.ingest("b", "ingested")
    # key never existed -> classified as a delete of a gone key
    _drain(plane, sink, 2)
    assert sink.records[1]["Records"][0]["eventName"] == \
        "s3:ObjectRemoved:Delete"
    plane.close()


def test_owner_hash_is_deterministic_and_stable():
    nodes = sorted(f"10.0.0.{i}:9000" for i in range(1, 6))
    owners = {b: _owner_of(b, nodes)
              for b in ("alpha", "beta", "gamma", "delta")}
    assert all(o in nodes for o in owners.values())
    assert owners == {b: _owner_of(b, nodes) for b in owners}
    # removing one node only moves the buckets it owned
    survivor_nodes = [n for n in nodes if n != owners["alpha"]]
    for b, o in owners.items():
        if o != owners["alpha"]:
            assert _owner_of(b, survivor_nodes) == o


# ---------------------------------------------------------------------------
# filters on the live plane + config gating
# ---------------------------------------------------------------------------

def test_plane_honors_prefix_suffix_filters(tmp_path):
    layer = _mk_layer(tmp_path)
    reg = NotifyTargetRegistry(layer)
    arn = new_arn("t", "queue")
    reg.add(NotifyTarget(arn=arn, type="queue"))
    sink = QueueSender(arn)
    reg.set_sender(arn, sink)
    plane = NotificationPlane(layer, reg, busy_fn=lambda: False)
    plane.set_config("b", _xml(arn, events=("s3:ObjectCreated:*",),
                               prefix="img/", suffix=".jpg"))
    layer.attach_notifications(plane)

    layer.put_object("b", "img/a.jpg", b"1")
    layer.put_object("b", "img/b.png", b"2")       # suffix miss
    layer.put_object("b", "doc/c.jpg", b"3")       # prefix miss
    layer.delete_object("b", "img/a.jpg")          # event-type miss
    assert plane.drain(30), plane.stats()
    assert [r["Records"][0]["s3"]["object"]["key"]
            for r in sink.records] == ["img/a.jpg"]

    # a bucket with no configuration enqueues nothing at all
    layer.make_bucket("quiet")
    q0 = plane.stats()["queued"]
    layer.put_object("quiet", "x", b"y")
    assert plane.drain(30)
    assert plane.stats()["queued"] == q0
    plane.close()
