"""Bit-identity of the batched device HighwayHash against the scalar
implementation (itself pinned to published vectors in test_bitrot.py),
plus the fused put_step (encode + digests) against the host oracle."""

import numpy as np
import pytest

from minio_tpu import bitrot as bitrot_mod
from minio_tpu.bitrot import MAGIC_HIGHWAYHASH_KEY as KEY
from minio_tpu.ops import rs_ref
from minio_tpu.ops.highwayhash_jax import hh256_batch
from minio_tpu.ops.highwayhash_py import HighwayHash


def _want(data: bytes) -> bytes:
    h = HighwayHash(KEY)
    h.update(data)
    return h.digest256()


# every remainder branch: 0, <4, mod4 0..3, the >=16 branch, exact
# packets, multi-packet, scan + leftover (each length is a separate XLA
# compile — keep the list lean but branch-complete)
@pytest.mark.parametrize("length", [
    0, 1, 3, 15, 16, 18, 21, 31, 32, 33, 100, 129, 1000,
])
def test_hh256_batch_identity(length):
    rng = np.random.default_rng(length)
    n = 4
    data = rng.integers(0, 256, (n, max(length, 1)), dtype=np.uint8)
    data = data[:, :length]
    got = np.asarray(hh256_batch(KEY, data))
    assert got.shape == (n, 32)
    for i in range(n):
        assert got[i].tobytes() == _want(data[i].tobytes()), f"row {i}"


def test_hh256_batch_matches_bitrot_hasher():
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (3, 87382), dtype=np.uint8)
    got = np.asarray(hh256_batch(KEY, data))
    for i in range(3):
        want = bitrot_mod.hash_shard(
            data[i], bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256)
        assert got[i].tobytes() == want


def test_put_step_fused_oracle():
    from minio_tpu.models.pipeline import put_step
    k, m = 4, 2
    s = 1031  # odd length exercises the remainder path
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (2, k, s), dtype=np.uint8)
    parity, digests = put_step(data, k, m)
    parity, digests = np.asarray(parity), np.asarray(digests)
    assert parity.shape == (2, m, s)
    assert digests.shape == (2, k + m, 32)
    for b in range(2):
        want = rs_ref.encode(data[b], m)
        assert (parity[b] == want[k:]).all()
        for row in range(k + m):
            assert digests[b, row].tobytes() == _want(want[row].tobytes())


def test_put_step_padded_shard_len():
    """Zero-padded columns must not change the digests of the true
    shard_len prefix (the engine pads S up for kernel alignment)."""
    from minio_tpu.models.pipeline import put_step
    k, m = 4, 2
    s, pad = 500, 140
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (1, k, s), dtype=np.uint8)
    padded = np.pad(data, ((0, 0), (0, 0), (0, pad)))
    par_p, dg_p = put_step(padded, k, m, s)
    par, dg = put_step(data, k, m)
    assert (np.asarray(par_p)[..., :s] == np.asarray(par)).all()
    assert (np.asarray(dg_p) == np.asarray(dg)).all()


def test_codec_fused_matches_cpu_path():
    """The engine's fused route must produce the same bytes the CPU path
    writes (digests + shards)."""
    from minio_tpu.object.codec import Codec
    codec = Codec(4, 2, 8192)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (3, 4, 2048), dtype=np.uint8)
    out = codec.encode_and_hash_batch(
        data, bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S, force="device")
    assert out is not None
    full, digests = out
    want_full = codec.encode_batch(data, force="numpy")
    assert (full == want_full).all()
    want_dg = bitrot_mod.hash_shards_batch(
        want_full.reshape(-1, 2048),
        bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S).reshape(3, 6, 32)
    assert (digests == want_dg).all()


def test_codec_fused_declines_unsupported_algo():
    from minio_tpu.object.codec import Codec
    codec = Codec(4, 2, 8192)
    data = np.zeros((1, 4, 64), dtype=np.uint8)
    assert codec.encode_and_hash_batch(
        data, bitrot_mod.BitrotAlgorithm.BLAKE2B512,
        force="device") is None


def test_codec_fused_sha256():
    import hashlib
    from minio_tpu.object.codec import Codec
    codec = Codec(4, 2, 8192)
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (2, 4, 1024), dtype=np.uint8)
    out = codec.encode_and_hash_batch(
        data, bitrot_mod.BitrotAlgorithm.SHA256, force="device")
    assert out is not None
    full, digests = out
    want_full = codec.encode_batch(data, force="numpy")
    assert (full == want_full).all()
    for b in range(2):
        for r in range(6):
            assert digests[b, r].tobytes() == hashlib.sha256(
                want_full[b, r].tobytes()).digest()


def test_codec_decode_stacked_matches_numpy():
    from minio_tpu.object.codec import Codec
    from minio_tpu.ops import rs_matrix, rs_ref
    codec = Codec(4, 2, 4 * 512)
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, (3, 4, 512), dtype=np.uint8)
    full = np.stack([rs_ref.encode(d, 2) for d in data])  # (3, 6, 512)
    # lose shards 0 and 3: survivors 1,2,4,5
    mask = sum(1 << i for i in (1, 2, 4, 5))
    _, used = rs_matrix.decode_matrix(4, 2, mask)
    stacked = np.stack([full[b][list(used)] for b in range(3)])
    for force in ("numpy", "device"):
        out = codec.decode_stacked(stacked, mask, force=force)
        assert (out == data).all(), force
