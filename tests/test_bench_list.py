"""CI smoke for bench.py --ab-list (tiny listing A/B): must run
end-to-end inside the tier-1 budget, emit JSON-serializable results,
prove the index serves pages identical to the merge-walk (the bench
asserts name-identity itself), beat the walk on page latency, and show
one crawler cycle doing ZERO merge walks once the index is attached."""

from __future__ import annotations

import json

import bench


def test_list_ab_smoke():
    out = bench.bench_list_ab(keys=150, drives=6, page=25,
                              versions_every=10)
    json.dumps(out)                     # BENCH-compatible payload
    assert out["config"]["keys"] == 150
    assert out["walk"]["pages"] == out["index"]["pages"] >= 6
    # the index slices memory; the walk re-runs a heap merge plus a
    # per-name quorum read per page — even on a loaded CI box the
    # index page must win clearly (full-size runs show >100x)
    assert out["page_p50_speedup_x"] > 3.0, out
    # one amortized walk: the crawler cycle re-walks nothing
    assert out["walk"]["cycle"]["merge_walks"] >= 3
    assert out["index"]["cycle"]["merge_walks"] == 0
    assert out["index"]["cycle"]["index_reads"] >= 3
    assert out["index"]["metacache"]["fallbacks"] == 0
    assert out["build_s"] >= 0
