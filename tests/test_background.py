"""Background plane: drive wipe -> auto format + sweep heal; dead slot
re-admission; data-usage crawler feeding quota (reference
background-newdisks-heal-ops.go / data-crawler.go test intents, and
buildscripts/verify-healing.sh's wipe-and-heal scenario)."""

from __future__ import annotations

import os
import shutil

import pytest

from minio_tpu.object.background import DataUsageCrawler, DiskMonitor
from minio_tpu.object.sets import ErasureSets


def _mk_sets(root, n=6, parity=2, **kw):
    drives = [str(root / f"d{i}") for i in range(n)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=n,
                                   parity=parity, block_size=1 << 16,
                                   **kw)
    return sets, drives


def test_wiped_drive_is_reformatted_and_swept(tmp_path):
    sets, drives = _mk_sets(tmp_path)
    sets.make_bucket("b")
    payload = os.urandom(150_000)
    sets.put_object("b", "obj", payload)
    sets.put_object("b", "obj2", b"x" * 1000)

    # wipe one drive entirely (format.json + shards gone)
    victim_idx = 2
    shutil.rmtree(drives[victim_idx])

    mon = DiskMonitor(sets)
    admitted = mon.scan_once()
    assert admitted == 1
    assert mon.healed_slots  # a fresh drive was formatted + swept

    # the wiped drive holds a valid format again, in the right slot
    from minio_tpu.storage.xl_storage import XLStorage
    d = XLStorage(drives[victim_idx])
    fmt = d.read_format()
    assert fmt.id == sets.deployment_id
    assert fmt.this in [u for row in sets.format_ref.sets for u in row]

    # its shards were rebuilt: objects readable with every OTHER drive
    # for the victim's set offline is the strong check — instead verify
    # the shard files exist on the healed drive
    names = d.list_dir("b", "obj")
    assert any("xl.meta" in n or n for n in names)
    _, stream = sets.get_object("b", "obj")
    assert b"".join(stream) == payload

    # second scan: steady state, nothing to admit
    assert mon.scan_once() == 0
    sets.close()


def test_dead_boot_slot_readmitted(tmp_path):
    # one root is a regular FILE: XLStorage(root) fails -> None slot
    bad = tmp_path / "d3"
    bad.write_bytes(b"not a dir")
    sets, drives = _mk_sets(tmp_path)
    assert sets.sets[0].disks.count(None) == 1
    sets.make_bucket("b")
    sets.put_object("b", "k", b"hello world" * 100)

    # the operator replaces the broken "drive"
    bad.unlink()
    mon = DiskMonitor(sets)
    assert mon.scan_once() == 1
    assert sets.sets[0].disks.count(None) == 0

    # healed: data now lands on all 6 drives
    _, stream = sets.get_object("b", "k")
    assert b"".join(stream) == b"hello world" * 100
    sets.close()


def test_monitor_never_adopts_foreign_drive(tmp_path):
    sets, drives = _mk_sets(tmp_path / "a")
    other, _ = _mk_sets(tmp_path / "b")
    # swap a drive of `sets` for one formatted by the OTHER deployment
    victim = 1
    shutil.rmtree(drives[victim])
    shutil.copytree(str((tmp_path / "b") / "d0"), drives[victim])
    mon = DiskMonitor(sets)
    assert mon.scan_once() == 0          # wrong deployment: not adopted
    sets.close()
    other.close()


def test_usage_crawler_and_quota(tmp_path):
    sets, _ = _mk_sets(tmp_path)
    sets.make_bucket("q1")
    sets.make_bucket("q2")
    sets.put_object("q1", "a", b"x" * 10_000)
    sets.put_object("q1", "b", b"y" * 5_000)
    sets.put_object("q2", "c", b"z" * 1_000)

    crawler = DataUsageCrawler(sets, persist=True)
    usage = crawler.scan_once()
    assert usage["buckets"]["q1"] == {"objects": 2, "size": 15_000}
    assert usage["buckets"]["q2"] == {"objects": 1, "size": 1_000}
    assert crawler.bucket_usage("q1") == 15_000
    assert crawler.bucket_usage("missing") == 0

    # snapshot persisted through the object layer
    snap = DataUsageCrawler.load_snapshot(sets)
    assert snap is not None and snap["size_total"] == 16_000

    # per-object actions fire for every object
    seen = []
    crawler.actions.append(lambda b, oi: seen.append((b, oi.name)))
    crawler.scan_once()
    assert ("q1", "a") in seen and ("q2", "c") in seen
    sets.close()


def test_quota_enforced_from_crawler_cache(tmp_path):
    from minio_tpu.s3.handlers import S3ApiHandlers
    from minio_tpu.s3.s3errors import S3Error
    sets, _ = _mk_sets(tmp_path)
    sets.make_bucket("qb")
    sets.put_object("qb", "base", b"d" * 8_000)
    api = S3ApiHandlers(sets)
    api.bucket_meta.update("qb", quota={"quota": 10000,
                                        "quotatype": "hard"})
    crawler = DataUsageCrawler(sets, persist=False)
    crawler.scan_once()
    api.usage = crawler

    api._enforce_quota("qb", 1_000)      # 8k + 1k < 10k: fine
    with pytest.raises(S3Error):
        api._enforce_quota("qb", 5_000)  # 8k + 5k > 10k
    sets.close()