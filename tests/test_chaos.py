"""Chaos tests: seeded NaughtyDisk schedules drive PUT/GET/heal/MRF
through drive faults (errors, bitrot flips, truncated streams, short
writes, offline windows) on <= parity drives.

Invariants (the acceptance bar of the failure-plane PR):
  * every op against the quorum-healthy set succeeds,
  * every object reads back byte-identical,
  * after MRF drain + a deep-scan heal pass, every shard verifies clean
    on every drive and the MRF queue is empty.

Every test prints its fault-schedule seed; a failing run reproduces
exactly via MINIO_TPU_CHAOS_SEED=<seed>. The cheap seeded subset runs
in tier-1; the long randomized schedules are additionally marked slow.
"""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage import XLStorage, errors as serr
from minio_tpu.storage.naughty import FaultSchedule, NaughtyDisk

pytestmark = pytest.mark.chaos

K, M = 4, 2
NDISKS = K + M
BLOCK = 1 << 16

# fast-converging MRF for tests: tight backoff, generous retries
MRF_TEST_OPTIONS = dict(max_retries=10, backoff_base=0.02,
                        backoff_max=0.25)


def chaos_seed(default: int) -> int:
    return int(os.environ.get("MINIO_TPU_CHAOS_SEED", "0") or 0) or default


def announce(seed: int) -> None:
    # pytest shows captured stdout on failure: the seed reproduces the
    # exact fault schedule (MINIO_TPU_CHAOS_SEED=<seed>)
    print(f"fault-schedule seed={seed} "
          f"(MINIO_TPU_CHAOS_SEED={seed} reproduces)")


def payload(size: int, seed: int = 7) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def make_chaos_sets(tmp_path, schedules: dict,
                    n: int = NDISKS, parity: int = M
                    ) -> tuple[ErasureSets, list[NaughtyDisk]]:
    """1 set x n drives; drives named in `schedules` get a (disarmed)
    NaughtyDisk wrapper — arm after the fixture is built."""
    drives: list = []
    naughty: list[NaughtyDisk] = []
    for j in range(n):
        d = XLStorage(str(tmp_path / f"d{j}"))
        sched = schedules.get(j)
        if sched is not None:
            nd = NaughtyDisk(d, schedule=sched, enabled=False)
            naughty.append(nd)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=n, parity=parity,
        block_size=BLOCK, mrf_options=dict(MRF_TEST_OPTIONS))
    sets.make_bucket("b")
    return sets, naughty


def run_workload(sets: ErasureSets, n_threads: int = 3,
                 n_objects: int = 4, seed: int = 0) -> dict[str, bytes]:
    """Concurrent PUT + immediate GET verify; returns {name: data}."""
    datas: dict[str, bytes] = {}
    failures: list = []

    def worker(t: int) -> None:
        for i in range(n_objects):
            name = f"o-{t}-{i}"
            size = (i % 3) * BLOCK + 1000 * (t + 1) + i * 37
            data = payload(size, seed=seed * 1000 + t * 100 + i)
            try:
                sets.put_object("b", name, data)
                _, it = sets.get_object("b", name)
                got = b"".join(it)
                if got != data:
                    failures.append((name, "byte mismatch"))
                datas[name] = data
            except Exception as e:  # noqa: BLE001 — collected for assert
                failures.append((name, repr(e)))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not failures, failures
    return datas


def assert_converged(sets: ErasureSets, datas: dict[str, bytes],
                     drain_timeout: float = 30.0) -> None:
    """MRF drain + deep-scan heal, then: queue empty, bytes identical,
    every shard verifies clean on every drive."""
    assert sets.drain_mrf(drain_timeout)
    for name in datas:
        sets.heal_object("b", name, deep_scan=True)
    assert sets.drain_mrf(drain_timeout)
    assert sets.mrf_stats()["pending"] == 0
    eng = sets.sets[0]
    for name, data in datas.items():
        _, it = sets.get_object("b", name)
        assert b"".join(it) == data, name
        for j, d in enumerate(eng.disks):
            fi = d.read_version("b", name)
            d.check_parts("b", name, fi)
            d.verify_file("b", name, fi)


# ---------------------------------------------------------------------------
# cheap seeded subset (tier-1)
# ---------------------------------------------------------------------------

def test_chaos_flaky_verbs_converge(tmp_path):
    """Random verb errors + read-bitrot on <= parity drives: no op may
    fail, bytes stay identical, MRF + heal converge every shard."""
    seed = chaos_seed(1101)
    announce(seed)
    sched = {j: FaultSchedule(seed=seed + j, error_rate=0.2,
                              bitrot_rate=0.15)
             for j in range(M)}
    sets, naughty = make_chaos_sets(tmp_path, sched)
    try:
        for nd in naughty:
            nd.arm()
        datas = run_workload(sets, seed=seed)
        for nd in naughty:
            nd.disarm()
        assert_converged(sets, datas)
    finally:
        sets.close()


def test_chaos_pipelined_put_writer_death_mid_batch(tmp_path,
                                                    monkeypatch):
    """Pipelined PUT with a drive dying MID-BATCH (an append_file frame
    write fails while later batches are still being ingested/encoded):
    write-quorum semantics hold — the PUT succeeds with <= parity
    writers lost, the 2-phase commit counts the dead drive, MRF is fed
    and heals the object back to full redundancy, bytes identical."""
    from minio_tpu.object import bitrot_io, engine as engine_mod
    from minio_tpu.parallel import pipeline as pl
    assert pl.ENABLED        # the default; the test targets this path
    # small batches + per-frame flushes so the failure lands inside
    # the write stage of a mid-stream batch, not at writer close
    monkeypatch.setattr(engine_mod, "ENCODE_BATCH_BLOCKS", 2)
    monkeypatch.setattr(bitrot_io.StreamingBitrotWriter,
                        "FLUSH_THRESHOLD", 1)
    seed = chaos_seed(2201)
    announce(seed)
    sets, naughty = make_chaos_sets(tmp_path,
                                    {0: FaultSchedule(seed=seed)})
    try:
        nd = naughty[0]
        nd.arm()
        # the 5th frame append on drive 0 fails: mid-stream, mid-batch
        nd.verb_errors["append_file"] = {5: serr.FaultyDisk("mid-batch")}
        data = payload(10 * BLOCK + 1234, seed=seed)
        sets.put_object("b", "o", data)
        assert nd.stats.calls.get("append_file", 0) >= 5
        stats = sets.mrf_stats()
        assert stats["queued"] >= 1        # degraded write fed MRF
        assert_converged(sets, {"o": data})
    finally:
        sets.close()


def test_chaos_truncated_streams_and_short_writes(tmp_path):
    """Truncated read streams (mid-stream disconnects) on one drive and
    silent short writes on another stay invisible to clients and heal
    clean."""
    seed = chaos_seed(2202)
    announce(seed)
    sched = {0: FaultSchedule(seed=seed, truncate_rate=0.4),
             1: FaultSchedule(seed=seed + 1, truncate_rate=0.3,
                              bitrot_rate=0.2)}
    sets, naughty = make_chaos_sets(tmp_path, sched)
    try:
        for nd in naughty:
            nd.arm()
        datas = run_workload(sets, seed=seed)
        for nd in naughty:
            nd.disarm()
        assert_converged(sets, datas)
    finally:
        sets.close()


def test_chaos_offline_window_comes_back(tmp_path):
    """A drive that goes offline mid-workload and comes back: writes
    succeed at quorum during the outage; the drive converges after."""
    seed = chaos_seed(3303)
    announce(seed)
    sched = {2: FaultSchedule(seed=seed, offline_windows=((5, 60),))}
    sets, naughty = make_chaos_sets(tmp_path, sched)
    try:
        for nd in naughty:
            nd.arm()
        datas = run_workload(sets, seed=seed)
        assert naughty[0].stats.offline_hits > 0
        for nd in naughty:
            nd.disarm()
        assert_converged(sets, datas)
    finally:
        sets.close()


def test_chaos_schedule_is_deterministic():
    """Identical seeds replay identical fault decisions; a different
    seed diverges — the reproduce-from-printed-seed guarantee."""
    a = FaultSchedule(seed=42, error_rate=0.3, bitrot_rate=0.3,
                      truncate_rate=0.3, latency_rate=0.3)
    b = FaultSchedule(seed=42, error_rate=0.3, bitrot_rate=0.3,
                      truncate_rate=0.3, latency_rate=0.3)
    c = FaultSchedule(seed=43, error_rate=0.3, bitrot_rate=0.3,
                      truncate_rate=0.3, latency_rate=0.3)

    def trace(s):
        return [(s.error_for("read_file", n) is not None,
                 s.corrupts("read_file", n), s.truncates("read_file", n),
                 s.latency_for("append_file", n) > 0)
                for n in range(200)]

    assert trace(a) == trace(b)
    assert trace(a) != trace(c)
    # the fault mix is actually exercised at these rates
    hits = trace(a)
    assert any(h[0] for h in hits) and any(h[1] for h in hits)
    assert any(h[2] for h in hits) and any(h[3] for h in hits)


# ---------------------------------------------------------------------------
# topology plane: rebalance under faults
# ---------------------------------------------------------------------------

def test_chaos_rebalance_pool_death_and_bitrot(tmp_path):
    """Pool drain under chaos: the whole TARGET pool dies mid-drain
    (every move fails at write quorum) and the source serves reads
    with bitrot on <= parity drives. Invariants: no write-quorum
    object is lost (everything stays readable from the source), failed
    moves land in the source MRF queue and count in
    minio_tpu_rebalance_failed_total; after the target recovers the
    drain converges and the source pool is empty."""
    from minio_tpu.object.rebalance import Rebalancer
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.topology import POOL_DRAINING
    from minio_tpu.utils import telemetry

    seed = chaos_seed(4404)
    announce(seed)
    # source: bitrot on read for <= parity drives (moves reconstruct)
    src_sched = {j: FaultSchedule(seed=seed + j, bitrot_rate=0.2,
                                  fault_verbs=("read_file",
                                               "read_file_stream"))
                 for j in range(M)}
    src, src_naughty = make_chaos_sets(tmp_path / "src", src_sched)
    # target: plain wrappers we can kill wholesale ("pool death")
    dst_drives = []
    dst_naughty = []
    for j in range(NDISKS):
        nd = NaughtyDisk(XLStorage(str(tmp_path / "dst" / f"d{j}")),
                         schedule=FaultSchedule(seed=seed + 100 + j),
                         enabled=False)
        dst_naughty.append(nd)
        dst_drives.append(nd)
    dst = ErasureSets.from_storage(dst_drives, 1, NDISKS, M, block_size=BLOCK,
                                   mrf_options=dict(MRF_TEST_OPTIONS))
    dst.make_bucket("b")
    zz = ErasureServerSets([src, dst])
    try:
        datas = {}
        for i in range(6):
            name = f"chaos-{i}"
            data = payload(BLOCK + 211 * i, seed=seed + i)
            src.put_object("b", name, data)
            datas[name] = data
        zz.set_pool_state(0, POOL_DRAINING)

        def failed_total():
            snap = telemetry.REGISTRY.snapshot(
                "minio_tpu_rebalance_failed_total")
            return snap.get("minio_tpu_rebalance_failed_total",
                            {}).get("pool=0", 0)

        failed_before = failed_total()
        # pool death: > parity target drives offline -> every move
        # fails its target write quorum
        for nd in dst_naughty[:M + 2]:
            nd.offline = True
        for nd in src_naughty:
            nd.arm()
        reb = Rebalancer(zz, 0, busy_fn=lambda: False)
        moved, failed, remaining = reb.run_pass()
        assert moved == 0 and failed == len(datas)
        assert remaining == len(datas)
        assert failed_total() - failed_before >= len(datas)
        # failed moves fed the source MRF queue
        assert src.mrf_stats()["queued"] >= 1
        # nothing lost: every object still reads byte-identical
        for name, data in datas.items():
            _, it = zz.get_object("b", name)
            assert b"".join(it) == data, name

        # target pool recovers: the drain converges
        for nd in dst_naughty:
            nd.offline = False
        src.drain_mrf(30.0)
        moved2, failed2, remaining2 = reb.run_pass(restart=True)
        assert failed2 == 0 and remaining2 == 0
        assert moved2 == len(datas)
        for nd in src_naughty:
            nd.disarm()
        assert src.list_object_versions("b", max_keys=20)[0] == []
        for name, data in datas.items():
            _, it = zz.get_object("b", name)
            assert b"".join(it) == data, name
            assert dst.has_object_versions("b", name)
    finally:
        zz.close()


# ---------------------------------------------------------------------------
# RemoteStorage drives: faults injected on the SERVER side of the RPC
# ---------------------------------------------------------------------------

def test_chaos_remote_storage_faults_over_rpc(tmp_path):
    """A NaughtyDisk schedule BEHIND storage_rpc: verb errors, bitrot
    and truncated streams are injected server-side, so every fault
    crosses the wire through the RPC error-mapping path (wire fault ->
    serr.* reconstruction, mid-stream truncation -> NetworkStorageError)
    instead of a local wrapper shortcut. Quorum ops succeed, bytes stay
    identical, MRF + heal converge every shard."""
    from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                                   StorageRPCServer)
    from minio_tpu.distributed.transport import RPCServer

    seed = chaos_seed(5505)
    announce(seed)
    ak, sk = "chaoskey", "chaossecret1234"
    naughty: list[NaughtyDisk] = []
    serving: dict[str, object] = {}
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j < M:
            nd = NaughtyDisk(d, schedule=FaultSchedule(
                seed=seed + j, error_rate=0.15, bitrot_rate=0.15,
                truncate_rate=0.15), enabled=False)
            naughty.append(nd)
            serving[f"/d{j}"] = nd
        else:
            serving[f"/d{j}"] = d
    rpc_srv = StorageRPCServer(serving, ak, sk)
    host = RPCServer().start()
    host.mount(rpc_srv.handler)
    remotes = [RemoteStorage("127.0.0.1", host.port, f"/d{j}", ak, sk)
               for j in range(NDISKS)]
    sets = ErasureSets.from_storage(
        remotes, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK, sources=list(remotes),
        mrf_options=dict(MRF_TEST_OPTIONS))
    sets.make_bucket("b")
    try:
        for nd in naughty:
            nd.arm()
        datas = run_workload(sets, seed=seed)
        for nd in naughty:
            nd.disarm()
        # the schedule really fired behind the RPC server
        injected = sum(nd.stats.errors + nd.stats.bitrot
                       + nd.stats.truncated for nd in naughty)
        assert injected > 0
        assert sets.drain_mrf(30.0)
        for name in datas:
            sets.heal_object("b", name, deep_scan=True)
        assert sets.drain_mrf(30.0)
        assert sets.mrf_stats()["pending"] == 0
        for name, data in datas.items():
            _, it = sets.get_object("b", name)
            assert b"".join(it) == data, name
            for d in sets.sets[0].disks:
                fi = d.read_version("b", name)
                d.check_parts("b", name, fi)
                d.verify_file("b", name, fi)
    finally:
        sets.close()
        for r in remotes:
            r.close()
        host.stop()


# ---------------------------------------------------------------------------
# long randomized schedules (nightly)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("base_seed", [101, 202, 303])
def test_chaos_randomized_full_mix(tmp_path, base_seed):
    """Everything at once on parity-many drives: verb errors, latency,
    bitrot, truncation, and an offline window — larger workload, full
    convergence."""
    seed = chaos_seed(base_seed)
    announce(seed)
    sched = {
        0: FaultSchedule(seed=seed, error_rate=0.25, latency_rate=0.1,
                         latency=0.001, bitrot_rate=0.2,
                         truncate_rate=0.15),
        1: FaultSchedule(seed=seed + 7, error_rate=0.15,
                         bitrot_rate=0.15, truncate_rate=0.1,
                         offline_windows=((30, 120), (220, 260))),
    }
    sets, naughty = make_chaos_sets(tmp_path, sched)
    try:
        for nd in naughty:
            nd.arm()
        datas = run_workload(sets, n_threads=4, n_objects=8, seed=seed)
        for nd in naughty:
            nd.disarm()
        assert_converged(sets, datas, drain_timeout=60.0)
    finally:
        sets.close()


# ---------------------------------------------------------------------------
# tier-plane chaos: NaughtyTierClient faults through transition/restore
# ---------------------------------------------------------------------------

def _tier_env(tmp_path, **worker_kw):
    from minio_tpu.tier.client import FSTierClient, NaughtyTierClient
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import TransitionWorker
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"d{i}") for i in range(NDISKS)], 1, NDISKS, M,
        block_size=BLOCK, mrf_options=MRF_TEST_OPTIONS)
    sets.make_bucket("b")
    tiers = TierManager(sets)
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    naughty = NaughtyTierClient(FSTierClient(str(tmp_path / "tier")))
    tiers.set_client("cold", naughty)
    worker = TransitionWorker(sets, tiers, busy_fn=lambda: False,
                              **worker_kw)
    return sets, tiers, naughty, worker


def test_chaos_failed_transition_lands_in_mrf_and_retries(tmp_path):
    """A tier that 5xxes the upload: the transition fails, the object
    stays fully readable locally, the failure lands in the MRF queue,
    and a retry after the tier recovers succeeds."""
    from minio_tpu.tier.client import TierClientError
    sets, tiers, naughty, worker = _tier_env(tmp_path)
    worker.start()
    body = payload(150_000)
    info = sets.put_object("b", "obj", body)

    naughty.fail_verbs["put"] = TierClientError("upstream 503")
    worker.enqueue("b", "obj", "", "cold", etag=info.etag)
    assert worker.drain(30), worker.stats()
    assert worker.stats()["failed"] == 1
    # failure fed the MRF queue (heal-first), and the object is intact
    assert sets.mrf.queued >= 1
    assert sets.drain_mrf(10)
    _, stream = sets.get_object("b", "obj")
    assert b"".join(stream) == body

    # tier recovers: the retry (next crawler pass re-finds it) succeeds
    naughty.clear_faults()
    worker.enqueue("b", "obj", "", "cold", etag=info.etag)
    assert worker.drain(30)
    assert worker.stats()["moved"] == 1
    from minio_tpu.object import api_errors
    with pytest.raises(api_errors.InvalidObjectState):
        sets.get_object("b", "obj")
    worker.close()
    sets.close()


def test_chaos_mid_transition_crash_leaves_object_readable(tmp_path):
    """A 'crash' between the remote upload and the stub rewrite (the
    verify head fails, so the commit never happens): the object stays
    fully readable locally and the orphaned remote copy was freed."""
    from minio_tpu.tier.client import TierClientError
    sets, tiers, naughty, worker = _tier_env(tmp_path)
    worker.start()
    body = payload(120_000, seed=11)
    info = sets.put_object("b", "crash", body)

    # upload succeeds, then the worker dies before the stub rewrite
    # (head raising models the process losing the tier mid-commit)
    naughty.fail_verbs["head"] = TierClientError("conn reset")
    worker.enqueue("b", "crash", "", "cold", etag=info.etag)
    assert worker.drain(30)
    assert worker.stats()["failed"] == 1
    assert naughty.calls["put"] == 1        # the upload DID happen
    _, stream = sets.get_object("b", "crash")
    assert b"".join(stream) == body          # fully readable locally
    # no orphaned metadata: the version is still a plain local object
    from minio_tpu.storage import datatypes as dt
    assert not dt.is_transitioned(
        sets.get_object_info("b", "crash").user_defined)
    worker.close()
    sets.close()


def test_chaos_short_read_on_restore_keeps_stub(tmp_path):
    """A tier stream that truncates mid-restore: the local put aborts
    (no short copy committed over the stub), the object still answers
    InvalidObjectState, and a clean retry restores the full bytes."""
    from minio_tpu.object import api_errors
    from minio_tpu.tier.client import TierClientError
    from minio_tpu.tier.transition import restore_object
    sets, tiers, naughty, worker = _tier_env(tmp_path)
    worker.start()
    body = payload(200_000, seed=23)
    info = sets.put_object("b", "trunc", body)
    worker.enqueue("b", "trunc", "", "cold", etag=info.etag)
    assert worker.drain(30)
    assert worker.stats()["moved"] == 1

    naughty.short_read_verbs = ("get",)
    with pytest.raises(TierClientError):
        restore_object(sets, tiers, "b", "trunc")
    assert naughty.stats["short_reads"] >= 1
    # the stub survived the failed restore
    with pytest.raises(api_errors.InvalidObjectState):
        sets.get_object("b", "trunc")

    naughty.clear_faults()
    restore_object(sets, tiers, "b", "trunc")
    oi, stream = sets.get_object("b", "trunc")
    assert b"".join(stream) == body
    assert oi.etag == info.etag
    worker.close()
    sets.close()


def test_chaos_transition_with_naughty_source_drives(tmp_path):
    """Faulted SOURCE drives (<= parity) under the transition read: the
    engine's reconstructing GET feeds the tier the correct bytes, and
    the restored object round-trips byte-identical."""
    from minio_tpu.tier.client import FSTierClient
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import TransitionWorker, restore_object
    from minio_tpu.object import api_errors
    seed = chaos_seed(4242)
    announce(seed)
    sets, naughties = make_chaos_sets(
        tmp_path, {0: FaultSchedule(seed=seed, error_rate=0.15),
                   1: FaultSchedule(seed=seed + 1, bitrot_rate=0.05)})
    body = payload(180_000, seed=seed & 0xFF)
    info = sets.put_object("b", "faulty", body)
    for nd in naughties:
        nd.arm()
    tiers = TierManager(sets)
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    worker = TransitionWorker(sets, tiers, busy_fn=lambda: False).start()
    worker.enqueue("b", "faulty", "", "cold", etag=info.etag)
    assert worker.drain(60), worker.stats()
    assert worker.stats()["moved"] == 1
    with pytest.raises(api_errors.InvalidObjectState):
        sets.get_object("b", "faulty")
    restore_object(sets, tiers, "b", "faulty")
    _, stream = sets.get_object("b", "faulty")
    assert b"".join(stream) == body
    for nd in naughties:
        nd.disarm()
    worker.close()
    sets.close()


# ---------------------------------------------------------------------------
# encrypted shards under bitrot: reconstruct or clean auth error — NEVER
# silently corrupted plaintext
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("device", [False, True])
def test_chaos_bitrot_on_encrypted_shards(tmp_path, monkeypatch, device):
    """NaughtyDisk bitrot under an encrypted object has exactly two
    legal outcomes: the shard digests catch the flip and the erasure
    layer reconstructs (plaintext byte-identical), or — when too many
    drives rot to reconstruct — the read fails with a clean error
    (erasure quorum or Poly1305 auth). A success that returns WRONG
    plaintext is the one forbidden outcome, on both cipher paths
    (device-fused PUT and the CPU fallback)."""
    from minio_tpu.features import crypto as sse
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object import engine as engine_mod

    if device:
        monkeypatch.setattr(codec_mod, "_IS_TPU", True)
        monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
        monkeypatch.setenv("MINIO_TPU_SSE_DEVICE_MIN_BYTES", "0")
    seed = chaos_seed(7801)
    announce(seed)
    oek, base = bytes(range(32)), bytes(range(50, 62))

    def decrypt_back(sets, name, n):
        """Full read path: erasure GET feeds the verify-then-decrypt
        seam exactly as the S3 handler does."""
        def fetch(off, ln):
            _, it = sets.get_object("b", name, off, ln)
            return it
        return b"".join(sse.chacha_decrypt_ranged(
            fetch, sse.encrypted_size(n), oek, base, 0, n))[:n]

    # phase 1: bitrot on <= parity drives -> reconstruct, byte-identical
    sched = {j: FaultSchedule(seed=seed + j, bitrot_rate=0.35,
                              fault_verbs=("read_file",
                                           "read_file_stream"))
             for j in range(M)}
    sets, naughty = make_chaos_sets(tmp_path / "lo", sched)
    datas = {}
    for i, n in enumerate((1000, BLOCK + 17, 2 * BLOCK + 999)):
        data = payload(n, seed=seed + i)
        sets.put_object("b", f"e{i}", data,
                        opts=engine_mod.PutOptions(
                            sse_spec=sse.DeviceSSE(oek, base)))
        datas[f"e{i}"] = data
    for nd in naughty:
        nd.arm()
    for name, data in datas.items():
        assert decrypt_back(sets, name, len(data)) == data, name
    for nd in naughty:
        nd.disarm()
    sets.close()

    # phase 2: bitrot past parity -> clean failure or correct bytes,
    # never a silent wrong-plaintext success
    sched = {j: FaultSchedule(seed=seed + 100 + j, bitrot_rate=1.0,
                              fault_verbs=("read_file",
                                           "read_file_stream"))
             for j in range(M + 1)}
    sets, naughty = make_chaos_sets(tmp_path / "hi", sched)
    n = BLOCK + 4321
    data = payload(n, seed=seed + 9)
    sets.put_object("b", "hot", data,
                    opts=engine_mod.PutOptions(
                        sse_spec=sse.DeviceSSE(oek, base)))
    for nd in naughty:
        nd.arm()
    try:
        got = decrypt_back(sets, "hot", n)
    except Exception as exc:  # noqa: BLE001 — ANY clean error is legal
        # quorum/bitrot error from the erasure layer, or the Poly1305
        # trailer refusing the corrupt ciphertext: both are clean
        # failures; the test only forbids garbled plaintext below
        print(f"clean failure (ok): {type(exc).__name__}: {exc}")
    else:
        assert got == data, "silent plaintext corruption leaked through"
    for nd in naughty:
        nd.disarm()
    sets.close()
