"""ChaCha20-Poly1305 SSE cipher: RFC 8439 vectors, byte-identity of the
three keystream implementations (scalar reference, vectorized numpy,
JAX device kernel), the detached-tag package stream transforms, and the
verify-then-decrypt ranged GET helper."""

from __future__ import annotations

import os
import secrets

import numpy as np
import pytest

from minio_tpu.features import crypto as sse
from minio_tpu.ops import chacha20_ref as c20

PKG = sse.PKG_SIZE
TAG = sse.TAG_SIZE


# ---------------------------------------------------------------------------
# RFC 8439 vectors
# ---------------------------------------------------------------------------

RFC_KEY = bytes(range(32))
RFC_NONCE = bytes.fromhex("000000090000004a00000000")


def test_rfc8439_block_function():
    # §2.3.2: ChaCha20 block, counter 1
    out = c20._block_scalar(RFC_KEY, RFC_NONCE, 1)
    assert out[:16] == bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4")
    assert out[-16:] == bytes.fromhex(
        "b5129cd1de164eb9cbd083e8a2503c4e")


def test_rfc8439_encryption():
    # §2.4.2: plaintext sunscreen, counter 1
    key = bytes(range(32))
    nonce = bytes.fromhex("000000000000004a00000000")
    pt = (b"Ladies and Gentlemen of the class of '99: If I could "
          b"offer you only one tip for the future, sunscreen would "
          b"be it.")
    ct = c20.xor_stream(pt, key, nonce, counter=1)
    assert ct[:16] == bytes.fromhex(
        "6e2e359a2568f98041ba0728dd0d6981")
    assert ct[-10:] == bytes.fromhex("b40b8eedf2785e42874d")
    assert c20.xor_stream(ct, key, nonce, counter=1) == pt


def test_rfc8439_poly1305_mac():
    # §2.5.2
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a8"
        "0103808afb0db2fd4abff6af4149f51b")
    tag = c20.poly1305_mac(b"Cryptographic Forum Research Group", key)
    assert tag == bytes.fromhex("a8061dc1305136c6c22b8baf0c0127a9")


def test_rfc8439_poly1305_key_gen():
    # §2.6.2
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("000000000001020304050607")
    otk = c20.poly1305_key_gen(key, nonce)
    assert otk == bytes.fromhex(
        "8ad5a08b905f81cc815040274ab29471"
        "a833b637e3fd0da508dbb8e2fdd1a646")


def test_rfc8439_aead_seal():
    # §2.8.2 adapted to detached form
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (b"Ladies and Gentlemen of the class of '99: If I could "
          b"offer you only one tip for the future, sunscreen would "
          b"be it.")
    ct, tag = c20.seal_detached(key, nonce, aad, pt)
    assert ct[:16] == bytes.fromhex(
        "d31a8d34648e60db7b86afbc53ef7ec2")
    assert tag == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert c20.open_detached(key, nonce, aad, ct, tag) == pt
    with pytest.raises(ValueError):
        c20.open_detached(key, nonce, aad, ct,
                          bytes(16))   # wrong tag: refuse BEFORE decrypt
    with pytest.raises(ValueError):
        c20.open_detached(key, nonce, aad,
                          ct[:-1] + bytes([ct[-1] ^ 1]), tag)


# ---------------------------------------------------------------------------
# property: scalar == vectorized numpy == JAX kernel
# ---------------------------------------------------------------------------

def test_keystream_scalar_vs_vectorized():
    rng = np.random.default_rng(11)
    for _ in range(8):
        key = rng.bytes(32)
        nonce = rng.bytes(12)
        ctr = int(rng.integers(0, 5))
        nblk = int(rng.integers(1, 9))
        vec = c20.keystream(key, nonce, ctr, nblk)
        ref = b"".join(c20._block_scalar(key, nonce, ctr + i)
                       for i in range(nblk))
        assert vec.tobytes() == ref


def test_jax_keystream_matches_reference():
    from minio_tpu.ops import chacha20_jax as cj
    rng = np.random.default_rng(12)
    for pkg_bytes, p, b in ((64, 1, 1), (256, 3, 2), (PKG, 2, 2)):
        keys = rng.integers(0, 1 << 32, (b, 8), dtype=np.uint32)
        nonces = rng.integers(0, 1 << 32, (b, p, 3), dtype=np.uint32)
        got = np.asarray(cj.keystream_u8(keys, nonces, p * pkg_bytes,
                                         pkg_bytes))
        for i in range(b):
            key = keys[i].astype("<u4").tobytes()
            want = b"".join(
                c20.keystream(key,
                              nonces[i, j].astype("<u4").tobytes(),
                              1, pkg_bytes // 64).tobytes()
                for j in range(p))
            assert got[i].tobytes() == want, (pkg_bytes, p, i)


def test_jax_keystream_xor_roundtrip():
    from minio_tpu.ops import chacha20_jax as cj
    rng = np.random.default_rng(13)
    b, p, pkg_bytes = 2, 2, 512
    data = rng.integers(0, 256, (b, p * pkg_bytes), dtype=np.uint8)
    keys = rng.integers(0, 1 << 32, (b, 8), dtype=np.uint32)
    nonces = rng.integers(0, 1 << 32, (b, p, 3), dtype=np.uint32)
    ct = np.asarray(cj.keystream_xor(data, keys, nonces, pkg_bytes))
    assert not np.array_equal(ct, data)
    back = np.asarray(cj.keystream_xor(ct, keys, nonces, pkg_bytes))
    assert np.array_equal(back, data)


def test_jax_rejects_unaligned_packages():
    from minio_tpu.ops import chacha20_jax as cj
    keys = np.zeros((1, 8), np.uint32)
    nonces = np.zeros((1, 1, 3), np.uint32)
    with pytest.raises(ValueError):
        cj.keystream_u8(keys, nonces, 63, 63)


# ---------------------------------------------------------------------------
# package stream transforms: CPU encryptor == DeviceSSE spec
# ---------------------------------------------------------------------------

def _cpu_stream(pt: bytes, oek: bytes, base: bytes) -> bytes:
    enc = sse.ChaChaEncryptor(oek, base)
    return enc.update(pt) + enc.finalize()


def _device_spec_stream(pt: bytes, oek: bytes, base: bytes,
                        row_bytes: int) -> bytes:
    """Drive a DeviceSSE spec the way the engine does: full rows via
    the in-place CPU fallback (byte-identical to the device kernel),
    tail + trailer via cpu_encrypt_tail/absorb/trailer."""
    spec = sse.DeviceSSE(oek, base)
    nfull = len(pt) // row_bytes
    out = b""
    if nfull:
        flat = np.frombuffer(bytearray(pt[:nfull * row_bytes]),
                             np.uint8).reshape(nfull, row_bytes)
        spec.cpu_encrypt_rows(flat, 0)
        for i in range(nfull):
            spec.absorb(flat[i])
        out = flat.tobytes()
    tail = pt[nfull * row_bytes:]
    if tail:
        arr = np.frombuffer(bytearray(tail), np.uint8)
        spec.cpu_encrypt_tail(arr, nfull * row_bytes)
        spec.absorb(arr)
        out += arr.tobytes()
    return out + spec.trailer()


def test_device_spec_matches_cpu_encryptor():
    rng = np.random.default_rng(14)
    oek, base = rng.bytes(32), rng.bytes(12)
    row = 2 * PKG
    for n in (0, 1, 63, 64, 65, PKG - 1, PKG, PKG + 1, row, row + 7,
              3 * PKG + 7777):
        pt = rng.bytes(n)
        assert _device_spec_stream(pt, oek, base, row) == \
            _cpu_stream(pt, oek, base), n


def test_random_keys_nonces_lengths_property():
    rng = np.random.default_rng(15)
    for _ in range(10):
        oek, base = rng.bytes(32), rng.bytes(12)
        n = int(rng.integers(0, 3 * PKG))
        pt = rng.bytes(n)
        stored = _cpu_stream(pt, oek, base)
        assert len(stored) == sse.encrypted_size(n)
        ct_len, npkg = sse.chacha_ct_len(len(stored))
        assert ct_len == n and npkg * TAG == len(stored) - n
        # decrypt-by-oracle: open every package detached
        got = b""
        for seq in range(npkg):
            pkg_ct = stored[seq * PKG:min((seq + 1) * PKG, ct_len)]
            tag = stored[ct_len + seq * TAG:ct_len + (seq + 1) * TAG]
            got += c20.open_detached(
                oek, sse._pkg_nonce(base, seq), sse._pkg_aad(seq),
                pkg_ct, tag)
        assert got == pt


def test_batch_params_match_pkg_nonce():
    oek, base = secrets.token_bytes(32), secrets.token_bytes(12)
    spec = sse.DeviceSSE(oek, base)
    keys, nonces = spec.batch_params(4 * PKG, 3, 2 * PKG)
    assert keys.shape == (3, 8) and nonces.shape == (3, 2, 3)
    for i in range(3):
        assert keys[i].astype("<u4").tobytes() == oek
        for j in range(2):
            seq = 4 + i * 2 + j
            assert nonces[i, j].astype("<u4").tobytes() == \
                sse._pkg_nonce(base, seq)


# ---------------------------------------------------------------------------
# ranged verify-then-decrypt (the GET seam)
# ---------------------------------------------------------------------------

def _fetcher(stored: bytes):
    def fetch(off, ln):
        yield stored[off:off + ln]
    return fetch


def test_chacha_decrypt_ranged_full_and_middle():
    rng = np.random.default_rng(16)
    oek, base = rng.bytes(32), rng.bytes(12)
    pt = rng.bytes(3 * PKG + 500)
    stored = _cpu_stream(pt, oek, base)
    full = b"".join(sse.chacha_decrypt_ranged(
        _fetcher(stored), len(stored), oek, base, 0, len(pt)))
    assert full == pt
    off, ln = PKG + 123, PKG + 77
    mid = b"".join(sse.chacha_decrypt_ranged(
        _fetcher(stored), len(stored), oek, base, off, ln))
    # yields from the covering package boundary; caller trims
    assert mid[off % PKG:off % PKG + ln] == pt[off:off + ln]


def test_chacha_decrypt_ranged_rejects_corruption():
    from minio_tpu.s3.s3errors import S3Error
    rng = np.random.default_rng(17)
    oek, base = rng.bytes(32), rng.bytes(12)
    pt = rng.bytes(2 * PKG + 100)
    stored = bytearray(_cpu_stream(pt, oek, base))
    stored[PKG + 5] ^= 0x40     # flip ciphertext inside package 1
    with pytest.raises(S3Error) as ei:
        b"".join(sse.chacha_decrypt_ranged(
            _fetcher(bytes(stored)), len(stored), oek, base,
            0, len(pt)))
    assert "authentication" in str(ei.value)
    # package 0 range stays readable: corruption is contained
    ok = b"".join(sse.chacha_decrypt_ranged(
        _fetcher(bytes(stored)), len(stored), oek, base, 0, PKG))
    assert ok == pt[:PKG]


def test_chacha_decrypt_ranged_rejects_tag_corruption():
    from minio_tpu.s3.s3errors import S3Error
    rng = np.random.default_rng(18)
    oek, base = rng.bytes(32), rng.bytes(12)
    pt = rng.bytes(PKG + 11)
    stored = bytearray(_cpu_stream(pt, oek, base))
    stored[-1] ^= 0x01          # flip last trailer byte
    with pytest.raises(S3Error):
        b"".join(sse.chacha_decrypt_ranged(
            _fetcher(bytes(stored)), len(stored), oek, base,
            PKG, 11))


# ---------------------------------------------------------------------------
# key sealing + cipher metadata
# ---------------------------------------------------------------------------

def test_chacha_seal_unseal_roundtrip_and_wrong_key():
    sealing = secrets.token_bytes(32)
    oek = secrets.token_bytes(32)
    sealed = sse.seal_key(sealing, oek, cipher=sse.CIPHER_CHACHA)
    assert sse.unseal_key(sealing, sealed,
                          cipher=sse.CIPHER_CHACHA) == oek
    with pytest.raises(Exception):
        sse.unseal_key(secrets.token_bytes(32), sealed,
                       cipher=sse.CIPHER_CHACHA)


def test_cipher_knob_selects_chacha(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_SSE_CIPHER", "chacha20")
    assert sse.sse_cipher_for_new_writes() == sse.CIPHER_CHACHA
    monkeypatch.setenv("MINIO_TPU_SSE_CIPHER", "aes-gcm")
    assert sse.sse_cipher_for_new_writes() == sse.CIPHER_AES
    assert sse.stored_sse_cipher(
        {sse.MK_CIPHER: sse.CIPHER_CHACHA}) == sse.CIPHER_CHACHA
    assert sse.stored_sse_cipher({}) == sse.CIPHER_AES
