"""Active-active replication plane: two-cluster in-process harness.

The acceptance battery of the replication subsystem
(minio_tpu/replicate/): concurrent writers on BOTH sites converge to
identical version listings, a replica-write counter proves loop
suppression (no ping-pong), resync seeds an empty site byte-identical
under a mid-resync crash + resume, transitioned stubs replicate as
metadata (never a 0-byte object) and pair through a shared tier
config, multipart objects cross sites with their part boundaries and
multipart etags, and the chaos tier (NaughtyReplClient 503 storms /
offline windows / mid-stream death) lands in the MRF retry queue and
drains clean on recovery.
"""

from __future__ import annotations

import io
import threading
import time

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions
from minio_tpu.object.multipart import CompletePart
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.replicate import (REPL_ORIGIN_KEY, LayerReplClient,
                                 NaughtyReplClient, ReplicationPlane,
                                 Resyncer, SiteTarget, TargetRegistry,
                                 new_arn)
from minio_tpu.replicate.client import (ReplClientError,
                                        ReplTargetOffline,
                                        replica_writes_counter)
from minio_tpu.utils.streams import IterStream


def _mk_site(root, name, buckets=("b",), drives=4):
    sets = ErasureSets.from_drives(
        [str(root / name / f"d{i}") for i in range(drives)],
        set_count=1, set_drive_count=drives, parity=2,
        block_size=1 << 16)
    layer = ErasureServerSets([sets], load_topology=False)
    for b in buckets:
        layer.make_bucket(b)
    reg = TargetRegistry(layer, site_id=name)
    plane = ReplicationPlane(layer, reg, busy_fn=lambda: False)
    layer.attach_replication(plane)
    return layer, reg, plane


def _pair(regA, A, regB, B, bucket="b"):
    """Wire two sites into an active-active pair; returns the ARNs."""
    arn_ab, arn_ba = new_arn(bucket), new_arn(bucket)
    regA.add(SiteTarget(arn=arn_ab, bucket=bucket, dest_bucket=bucket,
                        site=regB.site_id, type="layer"),
             client=LayerReplClient(B, bucket, regB.site_id))
    regB.add(SiteTarget(arn=arn_ba, bucket=bucket, dest_bucket=bucket,
                        site=regA.site_id, type="layer"),
             client=LayerReplClient(A, bucket, regA.site_id))
    return arn_ab, arn_ba


def _settle(*planes, rounds=4, timeout=30.0):
    """Drain every plane repeatedly: a replica apply re-fires the
    target's feed, so convergence needs a couple of rounds."""
    for _ in range(rounds):
        for p in planes:
            assert p.drain(timeout), p.stats()


def _listing(layer, bucket="b"):
    return [(v.name, v.version_id, round(v.mod_time, 6), v.etag,
             v.delete_marker)
            for v in layer.list_object_versions(bucket)[0]]


def _close(*planes):
    for p in planes:
        p.close()


def test_two_site_concurrent_writes_converge_and_no_pingpong(tmp_path):
    """The acceptance pin: concurrent writers on BOTH sites; both end
    with IDENTICAL list_object_versions listings, and the replica-
    write counters stay flat across extra sync cycles (a replicated
    write is never re-enqueued back at its origin)."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    _pair(regA, A, regB, B)

    def writer(layer, tag):
        for i in range(6):
            layer.put_object("b", f"k{i % 3}",
                             f"{tag}-{i}".encode() * 50,
                             opts=PutOptions(versioned=True))

    ta = threading.Thread(target=writer, args=(A, "a"))
    tb = threading.Thread(target=writer, args=(B, "b"))
    ta.start(); tb.start(); ta.join(); tb.join()
    _settle(planeA, planeB)

    la, lb = _listing(A), _listing(B)
    assert la == lb
    assert len(la) == 12                    # every version, both sides

    # loop suppression: every version was replica-written exactly once
    # at its non-origin site — and EXTRA sync cycles add none
    c = replica_writes_counter()
    wrote_a = c.value(site="siteA")
    wrote_b = c.value(site="siteB")
    assert wrote_a + wrote_b >= 12
    for i in range(3):
        planeA.on_namespace_change("b", f"k{i}")
        planeB.on_namespace_change("b", f"k{i}")
    _settle(planeA, planeB, rounds=2)
    assert c.value(site="siteA") == wrote_a
    assert c.value(site="siteB") == wrote_b
    assert _listing(A) == _listing(B)
    _close(planeA, planeB)


def test_markers_and_version_purge_converge(tmp_path):
    """A versioned delete (marker) replicates with its version id and
    origin metadata; purging a version at its origin prunes the
    replica at the peer (versioned deletes converge)."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    _pair(regA, A, regB, B)

    A.put_object("b", "doc", b"v1", opts=PutOptions(versioned=True))
    A.delete_object("b", "doc", versioned=True)       # marker at A
    _settle(planeA, planeB)
    la, lb = _listing(A), _listing(B)
    assert la == lb and any(m for (_, _, _, _, m) in la)
    # the replicated marker carries its origin (loop suppression +
    # prune both depend on marker metadata surviving xl.meta)
    mk = next(v for v in B.list_object_versions("b")[0]
              if v.delete_marker)
    assert (mk.user_defined or {}).get(REPL_ORIGIN_KEY) == "siteA"

    A.delete_object("b", "doc", version_id=mk.version_id)  # purge
    _settle(planeA, planeB)
    assert _listing(A) == _listing(B)
    assert not any(m for (_, _, _, _, m) in _listing(B))
    assert planeA.stats()["pruned"] >= 1

    # bulk delete rides the same feed (the unified-enqueue satellite:
    # the old per-handler hooks missed delete_objects entirely)
    for i in range(3):
        A.put_object("b", f"bulk/{i}", b"x", opts=PutOptions(versioned=True))
    _settle(planeA, planeB)
    assert len(_listing(B)) == len(_listing(A))
    A.delete_objects("b", [f"bulk/{i}" for i in range(3)])
    _settle(planeA, planeB)
    assert _listing(A) == _listing(B)
    _close(planeA, planeB)


def test_unversioned_lww_with_clock_skew(tmp_path):
    """Deterministic conflict rule on the unversioned slot: the higher
    (mod_time, version_id) wins at BOTH sites even when the writes race
    and the clocks disagree — enforced atomically inside the engine's
    write lock (PutOptions.if_none_newer), so an older replica can
    never clobber a newer local write."""
    A, regA, planeA = _mk_site(tmp_path, "siteA", buckets=("u",))
    B, regB, planeB = _mk_site(tmp_path, "siteB", buckets=("u",))
    _pair(regA, A, regB, B, bucket="u")

    t = time.time()
    A.put_object("u", "x", b"older", opts=PutOptions(mod_time=t - 10))
    B.put_object("u", "x", b"newer", opts=PutOptions(mod_time=t))
    _settle(planeA, planeB)
    got_a = b"".join(A.get_object("u", "x")[1])
    got_b = b"".join(B.get_object("u", "x")[1])
    assert got_a == got_b == b"newer"
    _close(planeA, planeB)


def test_multipart_replicates_with_part_boundaries(tmp_path):
    """A multipart object crosses sites through a REAL multipart
    replay: the remote part list matches the source and the recomputed
    multipart etag equals the origin's (the md5-of-part-md5s `-N`
    form), byte-identically."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    _pair(regA, A, regB, B)

    p1 = b"p" * (5 << 20)
    p2 = b"q" * (1 << 20)
    up = A.new_multipart_upload("b", "mp", PutOptions(versioned=True))
    e1 = A.put_object_part("b", "mp", up, 1, io.BytesIO(p1), len(p1)).etag
    e2 = A.put_object_part("b", "mp", up, 2, io.BytesIO(p2), len(p2)).etag
    info = A.complete_multipart_upload(
        "b", "mp", up, [CompletePart(1, e1), CompletePart(2, e2)])
    assert info.etag.endswith("-2")
    _settle(planeA, planeB, rounds=2, timeout=60)

    got = B.get_object_info("b", "mp")
    assert got.etag == info.etag
    assert [(p.number, p.size) for p in got.parts] == \
        [(1, len(p1)), (2, len(p2))]
    assert got.version_id == info.version_id
    assert got.mod_time == info.mod_time
    assert b"".join(B.get_object("b", "mp")[1]) == p1 + p2
    _close(planeA, planeB)


def test_transitioned_stub_seeds_as_metadata_and_tier_pairing(tmp_path):
    """A transitioned stub replicates as METADATA: the target never
    stores or serves a 0-byte object (GET answers InvalidObjectState),
    and a site sharing the tier config restores the real bytes."""
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import restore_object

    A, regA, planeA = _mk_site(tmp_path, "siteA")
    tiersA = TierManager(A)
    tiersA.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))

    A.put_object("b", "arch", b"z" * 4096, opts=PutOptions(versioned=True))
    oi = A.get_object_info("b", "arch")
    _, stream = A.get_object("b", "arch")
    rd = IterStream(stream)
    rk = tiersA.remote_key("b", "arch", oi.version_id)
    try:
        tiersA.client("cold").put(rk, rd, oi.size)
    finally:
        rd.close()
    A.transition_object("b", "arch", version_id=oi.version_id,
                        tier="cold", remote_object=rk,
                        expect_etag=oi.etag)

    # seed an EMPTY site (the stub is older than the pairing)
    D_sets = ErasureSets.from_drives(
        [str(tmp_path / "siteD" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    D = ErasureServerSets([D_sets], load_topology=False)
    arn = new_arn("b")
    regA.add(SiteTarget(arn=arn, bucket="b", dest_bucket="b",
                        site="siteD", type="layer"),
             client=LayerReplClient(D, "b", "siteD"))
    r = planeA.start_resync(arn, checkpoint_every=1, resume=False)
    for _ in range(200):
        if not r.running():
            break
        time.sleep(0.05)
    assert r.status()["status"] == "complete", r.status()

    sd = D.get_object_info("b", "arch")
    assert sd.size == 4096 and sd.etag == oi.etag   # never 0 bytes
    with pytest.raises(api_errors.InvalidObjectState):
        D.get_object("b", "arch")
    # tier-config pairing: same tier name registered at D -> the
    # remote copy fetches on restore
    tiersD = TierManager(D)
    tiersD.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    restore_object(D, tiersD, "b", "arch", version_id=sd.version_id)
    assert b"".join(D.get_object("b", "arch")[1]) == b"z" * 4096
    _close(planeA)


def test_resync_crash_resume_seeds_byte_identical(tmp_path):
    """Mid-resync crash + resume: the checkpointed walker continues
    from its marker and the seeded site ends byte-identical (markers
    and multipart objects included)."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    for i in range(14):
        A.put_object("b", f"seed/{i:02d}", f"v{i}".encode() * 64,
                     opts=PutOptions(versioned=True))
    A.delete_object("b", "seed/07", versioned=True)

    C_sets = ErasureSets.from_drives(
        [str(tmp_path / "siteC" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    C = ErasureServerSets([C_sets], load_topology=False)
    arn = new_arn("b")
    regA.add(SiteTarget(arn=arn, bucket="b", dest_bucket="b",
                        site="siteC", type="layer"),
             client=LayerReplClient(C, "b", "siteC"))

    r = Resyncer(A, regA, arn, plane=planeA, checkpoint_every=1,
                 page=4, resume=True)
    r.start()
    time.sleep(0.15)
    r.stop()                                # the "crash"
    st = r.status()
    assert st["status"] in ("stopped", "complete")

    r2 = Resyncer(A, regA, arn, plane=planeA, checkpoint_every=1,
                  page=4, resume=True)
    if st["status"] == "stopped" and st["keys_scanned"]:
        assert r2.state.get("resumed")      # picked up the checkpoint
    r2.start()
    for _ in range(400):
        if not r2.running():
            break
        time.sleep(0.05)
    assert r2.status()["status"] == "complete", r2.status()
    assert _listing(A) == _listing(C)
    for i in range(14):
        if i == 7:
            continue
        assert b"".join(C.get_object("b", f"seed/{i:02d}")[1]) == \
            f"v{i}".encode() * 64
    _close(planeA)


def test_registry_persists_and_survives_decommission(tmp_path):
    """The target registry recovers highest-epoch-wins from any
    surviving pool: registered targets (and the site id) outlive a
    decommission of the pool that first persisted them."""
    sets0 = ErasureSets.from_drives(
        [str(tmp_path / "p0" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    A = ErasureServerSets([sets0], load_topology=False)
    A.make_bucket("b")
    regA = TargetRegistry(A, site_id="siteA")
    regA.save()
    planeA = ReplicationPlane(A, regA, busy_fn=lambda: False)
    A.attach_replication(planeA)

    B, regB, planeB = _mk_site(tmp_path, "siteB")
    arn = new_arn("b")
    regA.add(SiteTarget(arn=arn, bucket="b", dest_bucket="b",
                        site="siteB", type="layer"),
             client=LayerReplClient(B, "b", "siteB"))

    # expand with a second pool, then drain pool 0 away entirely
    sets1 = ErasureSets.from_drives(
        [str(tmp_path / "p1" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    A.add_pool(sets1)
    A.start_decommission(0, busy_fn=lambda: False)
    for _ in range(400):
        st = A.rebalance_status().get("rebalance", {})
        if st.get("status") == "complete":
            break
        time.sleep(0.05)
    assert A.rebalance_status()["rebalance"]["status"] == "complete"

    # replication keeps working through (and after) the drain
    A.put_object("b", "post-decom", b"hello",
                 opts=PutOptions(versioned=True))
    _settle(planeA, planeB)
    assert b"".join(B.get_object("b", "post-decom")[1]) == b"hello"

    # a fresh registry (restart) recovers from the surviving pool
    reg2 = TargetRegistry(A)
    assert reg2.load()
    assert reg2.site_id == "siteA" and arn in reg2.targets
    _close(planeA, planeB)


def test_chaos_storm_offline_and_midstream_drain_clean(tmp_path):
    """NaughtyReplClient chaos: a 503 storm, a target-offline window,
    and a mid-stream push death all land in the plane's MRF retry
    queue and drain clean once the target recovers — with clock skew
    on the racing writes."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    arn = new_arn("b")
    naughty = NaughtyReplClient(
        LayerReplClient(B, "b", "siteB"),
        # 503-style storm: the first 3 applies fail outright
        verb_errors={"apply": {1: ReplClientError("HTTP 503"),
                               2: ReplClientError("HTTP 503"),
                               3: ReplClientError("HTTP 503")}},
        # and the first 2 version reads hit an offline window
        offline_until_call={"versions": 3})
    regA.add(SiteTarget(arn=arn, bucket="b", dest_bucket="b",
                        site="siteB", type="layer"), client=naughty)

    t = time.time()
    A.put_object("b", "skewed", b"payload-1",
                 opts=PutOptions(versioned=True, mod_time=t + 120))
    A.put_object("b", "skewed2", b"payload-2",
                 opts=PutOptions(versioned=True, mod_time=t - 120))
    assert planeA.drain(30)
    # failures were recorded and retried through the MRF queue
    stats = planeA.stats()
    assert stats["failed"] >= 1
    assert naughty.stats["errors"] + naughty.stats["offline"] >= 3
    assert planeA.mrf.drain(30), planeA.mrf.stats()
    assert stats["synced"] + planeA.stats()["synced"] >= 2
    la = _listing(A)
    assert _listing(B) == la and len(la) == 2

    # mid-stream death on the NEXT push, then recovery: the dead push
    # lands in the retry queue; once the wire heals, a re-touch of the
    # key (what a resync pass or any later mutation does) converges it
    naughty.clear_faults()
    naughty.die_midstream = True
    A.put_object("b", "big", b"x" * (1 << 18),
                 opts=PutOptions(versioned=True))
    deadline = time.time() + 20
    while time.time() < deadline and not naughty.stats["midstream_deaths"]:
        time.sleep(0.05)
    assert naughty.stats["midstream_deaths"] >= 1
    naughty.die_midstream = False
    planeA.on_namespace_change("b", "big")
    assert planeA.drain(60), planeA.stats()
    assert planeA.mrf.drain(60), planeA.mrf.stats()
    _settle(planeA, planeB, rounds=2)
    assert b"".join(B.get_object("b", "big")[1]) == b"x" * (1 << 18)
    _close(planeA, planeB)


def test_http_wire_end_to_end(tmp_path):
    """The wire form: a second site behind a real S3 endpoint — the
    spec header apply (owner-gated), the admin key-versions read, and
    the purge DELETE all round-trip through HTTPReplClient."""
    from minio_tpu.replicate.client import HTTPReplClient
    from minio_tpu.s3.admin import mount_admin
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server

    creds = Credentials("replwirekey1", "replwiresecret1")
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    dst_sets = ErasureSets.from_drives(
        [str(tmp_path / "dst" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    dst = ErasureServerSets([dst_sets], load_topology=False)
    srv = S3Server(dst, creds=creds).start()
    mount_admin(srv)
    # give the far side its own registry so /replicate answers a site
    dst_reg = TargetRegistry(dst, site_id="siteW")
    dst_plane = ReplicationPlane(dst, dst_reg, busy_fn=lambda: False)
    srv.api.replication = dst_plane
    try:
        target = SiteTarget(
            arn=new_arn("wbkt"), bucket="b", dest_bucket="wbkt",
            site="", type="s3",
            params={"host": "127.0.0.1", "port": srv.port,
                    "access_key": creds.access_key,
                    "secret_key": creds.secret_key})
        client = HTTPReplClient(target)
        assert client.remote_site() == "siteW"
        client.ensure_bucket()

        regA.add(target, client=client)
        A.put_object("b", "wired", b"over-the-wire",
                     opts=PutOptions(versioned=True))
        A.delete_object("b", "wired", versioned=True)
        assert planeA.drain(30), planeA.stats()
        assert planeA.mrf.drain(30), planeA.mrf.stats()

        vs = dst.list_object_versions("wbkt")[0]
        assert len(vs) == 2 and any(v.delete_marker for v in vs)
        data = next(v for v in vs if not v.delete_marker)
        assert b"".join(dst.get_object(
            "wbkt", "wired",
            opts=__import__("minio_tpu.object.engine",
                            fromlist=["GetOptions"])
            .GetOptions(version_id=data.version_id))[1]) == \
            b"over-the-wire"
        # purge the marker at the origin -> pruned over the wire
        mk = next(v for v in A.list_object_versions("b")[0]
                  if v.delete_marker)
        A.delete_object("b", "wired", version_id=mk.version_id)
        assert planeA.drain(30) and planeA.mrf.drain(30)
        vs2 = dst.list_object_versions("wbkt")[0]
        assert not any(v.delete_marker for v in vs2)
    finally:
        _close(planeA, dst_plane)
        srv.stop()


def test_offline_wire_target_lands_in_mrf(tmp_path):
    """A wire target that is DOWN maps to ReplTargetOffline: the sync
    fails into the retry queue instead of wedging a worker."""
    from minio_tpu.replicate.client import HTTPReplClient
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    target = SiteTarget(arn=new_arn("b"), bucket="b", dest_bucket="b",
                        type="s3",
                        params={"host": "127.0.0.1", "port": 1,
                                "access_key": "x", "secret_key": "y"})
    client = HTTPReplClient(target, timeout=0.5)
    with pytest.raises(ReplTargetOffline):
        client.key_versions("k")
    regA.add(target, client=client)
    A.put_object("b", "k", b"v", opts=PutOptions(versioned=True))
    # the sync queue empties (drain() also waits on the RETRY queue,
    # which cannot finish while the target stays down — poll the sync
    # side only, then check the failure landed in the retry queue)
    deadline = time.time() + 20
    while time.time() < deadline:
        s = planeA.stats()
        if s["failed"] >= 1 and s["pending"] == 0:
            break
        time.sleep(0.1)
    s = planeA.stats()
    assert s["failed"] >= 1 and s["pending"] == 0, s
    assert s["retry"]["pending"] >= 1          # parked for backoff retry
    _close(planeA)


def test_legacy_push_target_to_plain_s3(tmp_path):
    """A legacy bucket-metadata remote target (generic S3 endpoint, no
    peer wire surface) mounts as a one-way "push" target: mutations
    reach the remote through plain PUT/DELETE — the old
    ReplicationPool semantics carried into the plane."""
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    creds = Credentials("legacykey1234", "legacysecret1234")
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    dst_sets = ErasureSets.from_drives(
        [str(tmp_path / "plain" / f"d{i}") for i in range(4)],
        1, 4, 2, block_size=1 << 16)
    dst_sets.make_bucket("destb")
    srv = S3Server(dst_sets, creds=creds).start()   # plain S3, no admin
    try:
        arn = planeA.mount_target_entry({
            "arn": "arn:minio:replication::legacy1:destb",
            "host": "127.0.0.1", "port": srv.port, "bucket": "destb",
            "access_key": creds.access_key,
            "secret_key": creds.secret_key,
            "source_bucket": "b"})
        assert regA.get(arn).type == "push"
        assert regA.get(arn).bucket == "b"          # source, not dest

        A.put_object("b", "doc", b"legacy-bytes",
                     opts=PutOptions(versioned=True))
        assert planeA.drain(30), planeA.stats()
        assert planeA.mrf.drain(30), planeA.mrf.stats()
        assert b"".join(dst_sets.get_object("destb", "doc")[1]) == \
            b"legacy-bytes"

        A.delete_object("b", "doc", versioned=True)  # marker -> DELETE
        assert planeA.drain(30) and planeA.mrf.drain(30)
        with pytest.raises(api_errors.ObjectApiError):
            dst_sets.get_object_info("destb", "doc")
    finally:
        _close(planeA)
        srv.stop()


def test_token_bucket_paces_chunks_larger_than_burst():
    """A chunk bigger than one burst window paces across refills in
    installments instead of livelocking (the 1 MiB-block-under-small-
    budget case)."""
    from minio_tpu.utils.bandwidth import TokenBucket
    tb = TokenBucket(512 << 10)          # 512 KiB/s, burst = 512 KiB
    t0 = time.monotonic()
    tb.take(1 << 20)                     # 1 MiB chunk: 2 bursts' worth
    dt_s = time.monotonic() - t0
    assert dt_s < 5.0                    # finished (no livelock)...
    assert dt_s >= 0.5                   # ...but actually paced
    tb.set_rate(0)                       # unlimited: take returns fast
    t0 = time.monotonic()
    tb.take(100 << 20)
    assert time.monotonic() - t0 < 0.1


def test_null_version_pushes_its_own_bytes_under_versioned_history(
        tmp_path):
    """The null slot must replicate ITS bytes, not the latest
    version's: a pre-versioning null object shadowed by later
    versioned writes crosses sites byte-correct (an empty version id
    in the read path resolves to LATEST — the push must use the
    "null" sentinel)."""
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    A.put_object("b", "mixed", b"null-era-bytes")          # null slot
    A.put_object("b", "mixed", b"versioned-bytes",
                 opts=PutOptions(versioned=True))
    _pair(regA, A, regB, B)
    planeA.on_namespace_change("b", "mixed")
    _settle(planeA, planeB)
    assert _listing(A) == _listing(B)
    from minio_tpu.object.engine import GetOptions
    got_null = b"".join(B.get_object(
        "b", "mixed", opts=GetOptions(version_id="null"))[1])
    assert got_null == b"null-era-bytes"
    assert b"".join(B.get_object("b", "mixed")[1]) == b"versioned-bytes"
    _close(planeA, planeB)


def test_per_target_lag_surface(tmp_path):
    """ROADMAP item 4 remainder: the plane reports per-target queue
    depth, oldest-pending age, last-sync timestamp and last lag — the
    admin-plane JSON twin of minio_tpu_repl_lag_seconds{target}."""
    import time as _time
    A, regA, planeA = _mk_site(tmp_path, "siteA")
    B, regB, planeB = _mk_site(tmp_path, "siteB")
    arn_ab, _arn_ba = _pair(regA, A, regB, B)

    t0 = _time.time()
    A.put_object("b", "lagged", b"x" * 2048,
                 opts=PutOptions(versioned=True))
    _settle(planeA, planeB)

    st = planeA.target_status()
    assert arn_ab in st
    entry = st[arn_ab]
    assert entry["bucket"] == "b"
    assert entry["synced"] >= 1 and entry["failed"] == 0
    assert entry["last_sync"] >= t0
    assert entry["last_lag_s"] is not None and entry["last_lag_s"] >= 0
    assert entry["queue_depth"] == 0 and entry["oldest_pending_s"] == 0.0

    # a queued-but-unsynced key shows up as live depth + pending age
    planeA._stop.set()                      # park the workers
    planeA._stop.clear()
    with planeA._cond:
        planeA._queue.append(("b", "stuck", _time.time() - 5.0))
        planeA._pending.add(("b", "stuck"))
    entry = planeA.target_status()[arn_ab]
    assert entry["queue_depth"] == 1
    assert entry["oldest_pending_s"] >= 4.0
    with planeA._cond:
        planeA._queue.clear()
        planeA._pending.clear()

    # the histogram rides a per-target label
    from minio_tpu.utils import telemetry
    hist = telemetry.REGISTRY.histogram("minio_tpu_repl_lag_seconds")
    with hist._mu:
        labels = [dict(k) for k in hist._series]
    assert any(lbl.get("target") == arn_ab for lbl in labels)
    _close(planeA, planeB)
