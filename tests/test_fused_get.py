"""Fused verify+decode / verify+recover device programs and their engine
wiring (VERDICT r2 item 1).

The reference treats bitrot verification as inseparable from decode
(streamingBitrotReader.ReadAt inside Erasure.Decode,
cmd/bitrot-streaming.go:111-150 + cmd/erasure-decode.go:211); these tests
pin the device-fused forms (models/pipeline.get_step / heal_step) to the
host oracles and drive the engine's deferred-verify GET/heal paths end to
end, including bitrot injected after the deferral decision.
"""

import numpy as np
import pytest

from minio_tpu import bitrot as bitrot_mod
from minio_tpu.models import pipeline
from minio_tpu.object import codec as codec_mod
from minio_tpu.object.codec import Codec
from minio_tpu.ops import gf256, rs_matrix, rs_ref, rs_tpu

HH = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S


def make_batch(seed, b, k, s):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (b, k, s), dtype=np.int64).astype(np.uint8)


def encode_full(data_b, k, m):
    return np.stack([rs_ref.encode(blk, m) for blk in data_b])


# ---------------------------------------------------------------------------
# matrix + kernel identity
# ---------------------------------------------------------------------------

def test_missing_data_matrix_oracle():
    k, m = 4, 2
    data = make_batch(0, 1, k, 64)[0]
    full = rs_ref.encode(data, m)
    for lost in [(0,), (1, 3), (0, 4), (2, 5)]:
        mask = sum(1 << i for i in range(k + m) if i not in lost)
        dm, used, missing = rs_matrix.missing_data_matrix(k, m, mask)
        assert missing == tuple(i for i in lost if i < k)
        if not missing:
            assert dm.shape[0] == 0
            continue
        surv = np.stack([full[u] for u in used])
        got = gf256.gf_matmul(np.asarray(dm, np.uint8), surv)
        want = np.stack([full[i] for i in missing])
        assert (got == want).all()


def test_get_step_reconstructs_and_digests():
    k, m, s, b = 4, 2, 256, 3
    data = make_batch(1, b, k, s)
    full = encode_full(data, k, m)
    lost = (1, 4)
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    dm, used, missing = rs_matrix.missing_data_matrix(k, m, mask)
    surv = np.stack([full[:, u] for u in used], axis=1)  # (B, k, S)
    m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
    out, digests = pipeline.get_step(surv, m2, dm.shape[0], k, s)
    out, digests = np.asarray(out), np.asarray(digests)
    # reconstructed rows byte-identical
    for r, mi in enumerate(missing):
        assert (out[:, r] == full[:, mi]).all()
    # survivor digests match the streaming-bitrot host hash
    for bi in range(b):
        for col, u in enumerate(used):
            want = bitrot_mod.hash_shard(full[bi, u].tobytes(), HH)
            assert digests[bi, col].tobytes() == want


def test_get_step_short_shard_len():
    """Digests must cover only the true payload prefix (last block of a
    part is shorter than the padded column width)."""
    k, m, s, slen = 4, 2, 128, 77
    data = make_batch(2, 2, k, s)
    data[:, :, slen:] = 0
    full = encode_full(data, k, m)
    mask = sum(1 << i for i in range(k + m) if i != 0)
    dm, used, missing = rs_matrix.missing_data_matrix(k, m, mask)
    surv = np.stack([full[:, u] for u in used], axis=1)
    m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
    _out, digests = pipeline.get_step(surv, m2, dm.shape[0], k, slen)
    want = bitrot_mod.hash_shard(full[0, used[0]][:slen].tobytes(), HH)
    assert np.asarray(digests)[0, 0].tobytes() == want


def test_heal_step_recovers_and_digests_outputs():
    k, m, s, b = 4, 2, 256, 2
    data = make_batch(3, b, k, s)
    full = encode_full(data, k, m)
    lost = (0, 5)  # one data + one parity
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    rec, used, missing = rs_matrix.recover_matrix(k, m, mask)
    rec = np.ascontiguousarray(np.asarray(rec, np.uint8))
    surv = np.stack([full[:, u] for u in used], axis=1)
    m2 = rs_tpu._bit_expand_cached(rec.tobytes(), rec.shape)
    out, sdig, odig = pipeline.heal_step(surv, m2, rec.shape[0], k, s)
    out, sdig, odig = np.asarray(out), np.asarray(sdig), np.asarray(odig)
    for r, mi in enumerate(missing):
        assert (out[:, r] == full[:, mi]).all()
        for bi in range(b):
            want = bitrot_mod.hash_shard(full[bi, mi].tobytes(), HH)
            assert odig[bi, r].tobytes() == want
    for bi in range(b):
        for col, u in enumerate(used):
            want = bitrot_mod.hash_shard(full[bi, u].tobytes(), HH)
            assert sdig[bi, col].tobytes() == want


def test_codec_fused_wrappers_route_and_match():
    k, m, s = 4, 2, 192
    codec = Codec(k, m, k * s)
    data = make_batch(4, 3, k, s)
    full = encode_full(data, k, m)
    lost = (2, 4)
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _dm, used, missing = rs_matrix.missing_data_matrix(k, m, mask)
    surv = np.stack([full[:, u] for u in used], axis=1)

    # not device-routed -> None (CPU host path takes over)
    assert codec.verify_and_decode_batch(surv, mask, s, HH) is None

    got = codec.verify_and_decode_batch(surv, mask, s, HH, force="device")
    assert got is not None
    out, missing_idx, sdig = got
    assert tuple(missing_idx) == missing
    assert (out[:, 0] == full[:, missing[0]]).all()

    got2 = codec.verify_and_recover_batch(
        surv, mask, set(lost), s, HH, force="device")
    assert got2 is not None
    out2, idxs2, _sdig2, odig2 = got2
    assert tuple(idxs2) == tuple(sorted(lost))
    for r, mi in enumerate(idxs2):
        assert (out2[:, r] == full[:, mi]).all()
        want = bitrot_mod.hash_shard(full[0, mi].tobytes(), HH)
        assert odig2[0, r].tobytes() == want


# ---------------------------------------------------------------------------
# engine wiring: deferred verify through GET / heal
# ---------------------------------------------------------------------------

@pytest.fixture()
def dev_routed(monkeypatch):
    """Route every batch to the 'device' (XLA-CPU in tests) so the
    engine's deferred-verify fused paths run."""
    monkeypatch.setattr(codec_mod, "_device_is_tpu", lambda: True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)


def _engine(tmp_path):
    from tests.test_engine import make_engine
    e = make_engine(tmp_path)
    e.make_bucket("bucket")
    return e


def _payload(size, seed=11):
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def _shard_files(tmp_path, name):
    import glob
    import os
    return sorted(glob.glob(os.path.join(
        str(tmp_path), "d*", "bucket", name, "*", "part.1")))


def test_engine_get_fused_degraded(dev_routed, tmp_path):
    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(3 * BLOCK + 4321)
    eng.put_object("bucket", "obj", data)
    # kill two drives' shard files (k=4, m=2 tolerates 2)
    import os
    for f in _shard_files(tmp_path, "obj")[:2]:
        os.remove(f)
    _oi, it = eng.get_object("bucket", "obj")
    assert b"".join(it) == data


def test_engine_get_fused_detects_bitrot(dev_routed, tmp_path):
    """Corrupt one shard's payload: the deferred device verify must catch
    it, drop the shard, and still return correct bytes via hedged
    re-read + reconstruct."""
    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(2 * BLOCK + 99, seed=13)
    eng.put_object("bucket", "obj", data)
    # corrupt the drive holding DATA shard 0 (drive i holds shard
    # distribution[i]-1; a corrupted parity shard would never be read
    # on the healthy path)
    fi = eng._read_one("bucket", "obj")
    drive = fi.erasure.distribution.index(1)
    f = _shard_files(tmp_path, "obj")[drive]
    raw = bytearray(open(f, "rb").read())
    raw[40] ^= 0xFF  # inside the first frame's payload (digest is 0..31)
    open(f, "wb").write(bytes(raw))

    flagged = []
    eng.on_degraded_read = lambda b, o: flagged.append(o)
    _oi, it = eng.get_object("bucket", "obj")
    assert b"".join(it) == data
    assert "obj" in flagged  # bitrot must queue a heal


def test_engine_heal_fused_writes_identical_frames(dev_routed, tmp_path):
    """Fused heal (verify+recover+rehash on device) must write shard
    files byte-identical to the originals, including the streaming
    bitrot frame digests."""
    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(4 * BLOCK + 17, seed=17)
    eng.put_object("bucket", "obj", data)
    files = _shard_files(tmp_path, "obj")
    import os
    victims = files[1:3]
    originals = {f: open(f, "rb").read() for f in victims}
    for f in victims:
        os.remove(f)
        # drop xl.meta too so the drive reads as outdated
        os.remove(os.path.join(os.path.dirname(os.path.dirname(f)),
                               "xl.meta"))
    res = eng.heal_object("bucket", "obj")
    assert res.disks_healed == 2
    for f, want in originals.items():
        assert open(f, "rb").read() == want

    _oi, it = eng.get_object("bucket", "obj")
    assert b"".join(it) == data


def test_engine_heal_fused_survives_corrupt_survivor(dev_routed,
                                                     tmp_path):
    """A corrupt survivor during a fused heal must be detected by the
    deferred verify and healed around via the host rebuild path."""
    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(2 * BLOCK, seed=19)
    eng.put_object("bucket", "obj", data)
    files = _shard_files(tmp_path, "obj")
    import os
    victim = files[0]
    original = open(victim, "rb").read()
    os.remove(victim)
    os.remove(os.path.join(os.path.dirname(os.path.dirname(victim)),
                           "xl.meta"))
    # corrupt a different, healthy survivor
    f = files[3]
    raw = bytearray(open(f, "rb").read())
    raw[45] ^= 0x55
    open(f, "wb").write(bytes(raw))

    res = eng.heal_object("bucket", "obj")
    assert res.disks_healed == 1
    assert open(victim, "rb").read() == original


def test_engine_get_defer_uses_stored_algo(dev_routed, tmp_path):
    """Frames written under one bitrot algorithm must verify with THAT
    algorithm even after the server's configured algo changes (review
    r3: deferred verify compared against self.bitrot_algo)."""
    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(2 * BLOCK + 5, seed=23)
    eng.put_object("bucket", "obj", data)          # HH256S frames
    eng.bitrot_algo = bitrot_mod.BitrotAlgorithm.SHA256
    _oi, it = eng.get_object("bucket", "obj")
    assert b"".join(it) == data


def test_engine_heal_declined_bucket_still_verifies(dev_routed,
                                                    monkeypatch,
                                                    tmp_path):
    """A heal group whose fused device call declines (tail bucket below
    the device size gate) must still verify the deferred survivor
    digests — otherwise bitrot gets laundered into freshly-digested
    healed shards (review r3 finding 1)."""
    from minio_tpu.object import healing as healing_mod
    from tests.test_engine import BLOCK
    eng = _engine(tmp_path)
    data = _payload(5 * BLOCK, seed=29)            # 5 blocks: groups 4+1
    eng.put_object("bucket", "obj", data)
    fi = eng._read_one("bucket", "obj")
    dist = fi.erasure.distribution
    files = _shard_files(tmp_path, "obj")

    shard_size = fi.erasure.shard_size()
    # defer on (4-block group >= gate) but 1-block tail bucket declines
    gate = 3 * 4 * shard_size
    monkeypatch.setattr(healing_mod, "HEAL_BATCH_BLOCKS", 4)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", gate)

    import os
    victim = files[dist.index(6)]                  # drive w/ last shard
    original = open(victim, "rb").read()
    os.remove(victim)
    os.remove(os.path.join(os.path.dirname(os.path.dirname(victim)),
                           "xl.meta"))
    # corrupt survivor shard 0's LAST block frame (the tail bucket)
    f = files[dist.index(1)]
    raw = bytearray(open(f, "rb").read())
    frame = 32 + shard_size
    raw[4 * frame + 32 + 5] ^= 0x77
    open(f, "wb").write(bytes(raw))

    res = eng.heal_object("bucket", "obj")
    assert res.disks_healed == 1
    assert open(victim, "rb").read() == original   # no laundered bitrot


def test_engine_get_decode_rides_batch_former(dev_routed, tmp_path):
    """With a scheduler attached, degraded-GET decode buckets must go
    through the cross-request former (decode verb dispatches > 0) and
    still return byte-identical data; concurrent degraded GETs of one
    object coalesce their buckets."""
    import threading
    from minio_tpu.parallel.scheduler import BatchScheduler

    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(3 * BLOCK + 777, seed=31)
    eng.put_object("bucket", "obj", data)
    import os
    for f in _shard_files(tmp_path, "obj")[:2]:
        os.remove(f)
    sched = BatchScheduler(max_batch=64, max_wait=0.1)
    eng.scheduler = sched
    try:
        outs: list = [None] * 3

        def read(i):
            _oi, it = eng.get_object("bucket", "obj")
            outs[i] = b"".join(it)

        threads = [threading.Thread(target=read, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(o == data for o in outs)
        st = sched.stats()["verbs"]["decode"]
        assert st["batches"] >= 1         # decode rode the former
        assert st["coalesced"] >= 1       # concurrent GETs fused
    finally:
        eng.scheduler = None
        sched.close()


def test_engine_heal_recover_rides_batch_former(dev_routed, tmp_path):
    """Bulk heal reconstruction must route its fused
    verify+recover+rehash buckets through the former and write frames
    byte-identical to the originals."""
    from minio_tpu.parallel.scheduler import BatchScheduler

    eng = _engine(tmp_path)
    from tests.test_engine import BLOCK
    data = _payload(4 * BLOCK + 33, seed=37)
    eng.put_object("bucket", "obj", data)
    files = _shard_files(tmp_path, "obj")
    import os
    victim = files[2]
    original = open(victim, "rb").read()
    os.remove(victim)
    os.remove(os.path.join(os.path.dirname(os.path.dirname(victim)),
                           "xl.meta"))
    sched = BatchScheduler(max_batch=64, max_wait=0.05)
    eng.scheduler = sched
    try:
        res = eng.heal_object("bucket", "obj")
        assert res.disks_healed == 1
        assert open(victim, "rb").read() == original
        assert sched.stats()["verbs"]["recover"]["batches"] >= 1
    finally:
        eng.scheduler = None
        sched.close()
