"""tools/check — the project-invariant linter: every rule provably
fires on a seeded bad fixture, stays quiet on the good twin, honors
suppressions, and the runner exits 0 on the committed tree (the smoke
pin that keeps the CI gate from silently rotting)."""

from __future__ import annotations

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from check import knobtable, rules_ast, rules_project, run as check_run  # noqa: E402
from check.core import Source  # noqa: E402

from minio_tpu.utils import knobs  # noqa: E402


def _src(rel: str, text: str) -> Source:
    return Source("<fixture>", rel, text)


# ---------------------------------------------------------------------------
# rule: lock-blocking
# ---------------------------------------------------------------------------

BAD_LOCK = '''
import os, time, json, shutil
class M:
    def hot(self):
        with self._mu:
            time.sleep(0.1)
    def io(self):
        with self._cond:
            open("/tmp/x")
            os.replace("a", "b")
            shutil.rmtree("d")
    def layer(self):
        with self._lock:
            self.obj.put_object("b", "k", b"")
    def dev(self):
        with self._mu:
            self.codec.encode_and_hash_batch(None, None)
    def fut(self):
        with self._mu:
            self.f.result()
    def evwait(self):
        with self._mu:
            self.event.wait(1)
    def _write_meta(self):
        json.dump({}, open("m", "w"))
    def indirect(self):
        with self._mu:
            self._write_meta()
'''

GOOD_LOCK = '''
import time
class M:
    def ok(self):
        with self._mu:
            self.x = 1
        time.sleep(0.1)
    def condwait(self):
        with self._cond:
            self._cond.wait(0.2)
    def kick(self):
        with self._mu:
            self._kick.wait(0.1)
    def later(self):
        with self._mu:
            def cb():
                open("/tmp/x")
            self.cb = cb
'''


def test_lock_rule_fires_on_every_banned_class():
    vs = rules_ast.check_lock_blocking(
        [_src("minio_tpu/object/metacache.py", BAD_LOCK)])
    msgs = "\n".join(v.message for v in vs)
    assert "time.sleep" in msgs
    assert "open()" in msgs
    assert "os.replace" in msgs
    assert "shutil.rmtree" in msgs
    assert ".put_object()" in msgs
    assert ".encode_and_hash_batch()" in msgs
    assert ".result()" in msgs
    assert ".wait()" in msgs
    assert "_write_meta() which performs" in msgs      # helper indirection
    assert len(vs) >= 9


def test_lock_rule_quiet_on_good_and_non_hot_modules():
    assert rules_ast.check_lock_blocking(
        [_src("minio_tpu/object/metacache.py", GOOD_LOCK)]) == []
    # the same bad code outside the designated hot list is not flagged
    assert rules_ast.check_lock_blocking(
        [_src("minio_tpu/features/events.py", BAD_LOCK)]) == []


def test_lock_rule_flags_manual_acquire():
    """`x.acquire(); try/finally` holds the lock invisibly to the
    with-body scan — the spelling itself is flagged, and a deliberate
    site argues its suppression inline."""
    code = ('class M:\n'
            '    def manual(self):\n'
            '        self._mu.acquire()\n'
            '        try:\n'
            '            pass\n'
            '        finally:\n'
            '            self._mu.release()\n')
    vs = rules_ast.check_lock_blocking(
        [_src("minio_tpu/object/metacache.py", code)])
    assert len(vs) == 1 and "manual self._mu.acquire()" in vs[0].message
    ok = code.replace(
        "        self._mu.acquire()\n",
        "        # check: allow(lock-blocking) argued reason\n"
        "        self._mu.acquire()\n")
    # suppression applies via the runner's filter; the raw rule still
    # reports — mirror run_checks' filtering here
    from check.core import filter_allowed
    src = _src("minio_tpu/object/metacache.py", ok)
    assert filter_allowed(src, rules_ast.check_lock_blocking([src])) == []


def test_lock_rule_suppression_on_with_line():
    code = ('import time\n'
            'class M:\n'
            '    def hot(self):\n'
            '        with self._mu:  '
            '# check: allow(lock-blocking) argued reason here\n'
            '            time.sleep(0.1)\n')
    assert rules_ast.check_lock_blocking(
        [_src("minio_tpu/object/metacache.py", code)]) == []


# ---------------------------------------------------------------------------
# rule: metrics-hygiene
# ---------------------------------------------------------------------------

BAD_METRICS = '''
from ..utils import telemetry
def hot_path():
    telemetry.REGISTRY.counter("minio_tpu_per_call_total", "h").inc()
C = telemetry.REGISTRY.counter("minio_tpu_badname", "h")
G = telemetry.REGISTRY.gauge("minio_tpu_twice_total", "h")
H = telemetry.REGISTRY.counter("minio_tpu_twice_total", "other help")
def a():
    C.inc(verb="x")
def b():
    C.inc(lane="y")
'''

GOOD_METRICS = '''
from ..utils import telemetry
C = telemetry.REGISTRY.counter("minio_tpu_good_total", "h")
_F = None
def _resolver_counter():
    global _F
    if _F is None:
        _F = telemetry.REGISTRY.counter("minio_tpu_memo_total", "h")
    return _F
def _collect_things():
    telemetry.REGISTRY.gauge("minio_tpu_live", "h").set(1)
class X:
    def __init__(self):
        self.h = telemetry.REGISTRY.histogram("minio_tpu_lat_seconds", "h")
def use():
    C.inc(verb="a")
def use2():
    C.inc(2, verb="b")
'''


def test_metrics_rule_fires():
    vs = rules_ast.check_metrics_hygiene(
        [_src("minio_tpu/object/zz.py", BAD_METRICS)])
    msgs = "\n".join(v.message for v in vs)
    assert "resolved inside hot_path()" in msgs
    assert "must end in `_total`" in msgs
    assert "ends in `_total` but is not a Counter" in msgs
    assert "one family, one kind" in msgs or "different help" in msgs
    assert "label sets must be consistent" in msgs


def test_metrics_rule_quiet_on_good():
    assert rules_ast.check_metrics_hygiene(
        [_src("minio_tpu/object/zz.py", GOOD_METRICS)]) == []


# ---------------------------------------------------------------------------
# rule: knob-env
# ---------------------------------------------------------------------------

BAD_KNOBS = '''
import os
A = os.environ.get("MINIO_TPU_SOMETHING", "1")
B = os.getenv("MINIO_TPU_OTHER")
C = "MINIO_TPU_FLAG" in os.environ
D = os.environ["MINIO_TPU_SUB"]
from ..utils import knobs
E = knobs.get_int("MINIO_TPU_NOT_REGISTERED")
'''

GOOD_KNOBS = '''
import os
from ..utils import knobs
A = knobs.get_int("MINIO_TPU_SCHED_MAX_BATCH")
B = os.environ.get("JAX_PLATFORMS", "")      # non-knob env is fine
'''


def test_knob_rule_fires_on_every_raw_read_form():
    vs = rules_ast.check_knob_env(
        [_src("minio_tpu/object/zz.py", BAD_KNOBS)], set(knobs.KNOBS))
    assert len(vs) == 5
    msgs = "\n".join(v.message for v in vs)
    assert "MINIO_TPU_SOMETHING" in msgs
    assert "MINIO_TPU_NOT_REGISTERED" in msgs


def test_knob_rule_quiet_on_good_and_inside_knobs_py():
    assert rules_ast.check_knob_env(
        [_src("minio_tpu/object/zz.py", GOOD_KNOBS)],
        set(knobs.KNOBS)) == []
    # knobs.py itself is the sanctioned home of RAW reads — only the
    # unregistered-getter-name check still applies there
    vs = rules_ast.check_knob_env(
        [_src("minio_tpu/utils/knobs.py", BAD_KNOBS)],
        set(knobs.KNOBS))
    assert len(vs) == 1 and "MINIO_TPU_NOT_REGISTERED" in vs[0].message


# ---------------------------------------------------------------------------
# rule: hook-coverage
# ---------------------------------------------------------------------------

ENGINE_OK = '''
class ErasureObjects:
    def put_object(self, b, k, r):
        return self._put(b, k)
    def _put(self, b, k):
        self._notify_degraded(b, k, "")
        self._notify_namespace(b, k)
    def update_object_metadata(self, b, k):
        self._notify_degraded(b, k, "")
        self._notify_namespace(b, k)
    def transition_object(self, b, k):
        self._notify_degraded(b, k, "")
        self._notify_namespace(b, k)
    def put_stub_version(self, b, k):
        self._notify_degraded(b, k, "")
        self._notify_namespace(b, k)
    def delete_object(self, b, k):
        self._flag_degraded_delete(b, k, "", [])
        self._notify_namespace(b, k)
    def put_delete_marker(self, b, k):
        self._flag_degraded_delete(b, k, "", [])
        self._notify_namespace(b, k)
    def delete_objects(self, b, ks):
        self._flag_degraded_delete(b, "", "", [])
        self._notify_namespace(b, "")
'''

MULTIPART_OK = '''
class MultipartMixin(ErasureObjects):
    def complete_multipart_upload(self, b, k, u, parts):
        self._notify_degraded(b, k, "")
        self._notify_namespace(b, k)
'''


def test_hook_rule_green_on_complete_fixture_and_fires_on_gap():
    ok = [_src("minio_tpu/object/engine.py", ENGINE_OK),
          _src("minio_tpu/object/multipart.py", MULTIPART_OK)]
    assert rules_project.check_hook_coverage(ok) == []
    # drop the namespace hook from delete_object -> flagged
    broken = ENGINE_OK.replace(
        '    def delete_object(self, b, k):\n'
        '        self._flag_degraded_delete(b, k, "", [])\n'
        '        self._notify_namespace(b, k)\n',
        '    def delete_object(self, b, k):\n'
        '        self._flag_degraded_delete(b, k, "", [])\n')
    vs = rules_project.check_hook_coverage(
        [_src("minio_tpu/object/engine.py", broken),
         _src("minio_tpu/object/multipart.py", MULTIPART_OK)])
    assert any("delete_object() never fires _notify_namespace" in v.message
               for v in vs)
    # drop the degraded hook from put_object's helper -> flagged
    broken2 = ENGINE_OK.replace(
        '    def _put(self, b, k):\n'
        '        self._notify_degraded(b, k, "")\n',
        '    def _put(self, b, k):\n')
    vs2 = rules_project.check_hook_coverage(
        [_src("minio_tpu/object/engine.py", broken2),
         _src("minio_tpu/object/multipart.py", MULTIPART_OK)])
    assert any("put_object() never fires on_degraded_write" in v.message
               for v in vs2)


def test_hook_rule_green_on_real_tree():
    from check.core import load_sources
    assert rules_project.check_hook_coverage(load_sources()) == []


# ---------------------------------------------------------------------------
# rule: error-map
# ---------------------------------------------------------------------------

API_ERRORS_FIX = '''
class ObjectApiError(Exception):
    pass
class Mapped(ObjectApiError):
    pass
class Internal(ObjectApiError):
    pass
class Orphan(ObjectApiError):
    pass
'''

S3_ERRORS_FIX = '''
ERROR_TABLE: dict = {
    "MappedCode": (400, "m"),
}
INTERNAL_ONLY = (oerr.Internal,)
def api_error_from(exc):
    mapping = [
        (oerr.Mapped, "MappedCode"),
    ]
'''


def test_error_rule_fires_on_orphan_and_bad_code():
    vs = rules_project.check_error_map(
        [_src("minio_tpu/object/api_errors.py", API_ERRORS_FIX),
         _src("minio_tpu/s3/s3errors.py", S3_ERRORS_FIX)])
    assert any("Orphan has no api_error_from mapping" in v.message
               for v in vs)
    assert not any("Mapped has no" in v.message for v in vs)
    assert not any("Internal has no" in v.message for v in vs)
    # a mapping to a code missing from ERROR_TABLE is flagged
    bad = S3_ERRORS_FIX.replace('"MappedCode")', '"GhostCode")')
    vs2 = rules_project.check_error_map(
        [_src("minio_tpu/object/api_errors.py", API_ERRORS_FIX),
         _src("minio_tpu/s3/s3errors.py", bad)])
    assert any("GhostCode" in v.message for v in vs2)
    # a literal S3Error("Unknown") anywhere is flagged
    handler = 'def h():\n    raise S3Error("NoSuchCode")\n'
    vs3 = rules_project.check_error_map(
        [_src("minio_tpu/object/api_errors.py", API_ERRORS_FIX),
         _src("minio_tpu/s3/s3errors.py", S3_ERRORS_FIX),
         _src("minio_tpu/s3/handlers.py", handler)])
    assert any("NoSuchCode" in v.message for v in vs3)


def test_error_rule_green_on_real_tree():
    from check.core import load_sources
    assert rules_project.check_error_map(load_sources()) == []


# ---------------------------------------------------------------------------
# the knob registry itself
# ---------------------------------------------------------------------------

def test_knob_typed_getters_and_fallbacks(monkeypatch):
    assert knobs.get_int("MINIO_TPU_SCHED_MAX_BATCH") == 32
    monkeypatch.setenv("MINIO_TPU_SCHED_MAX_BATCH", "64")
    assert knobs.get_int("MINIO_TPU_SCHED_MAX_BATCH") == 64
    monkeypatch.setenv("MINIO_TPU_SCHED_MAX_BATCH", "garbage")
    assert knobs.get_int("MINIO_TPU_SCHED_MAX_BATCH") == 32   # fallback
    monkeypatch.setenv("MINIO_TPU_METACACHE", "off")
    assert knobs.get_bool("MINIO_TPU_METACACHE") is False
    monkeypatch.setenv("MINIO_TPU_METACACHE", "weird")
    assert knobs.get_bool("MINIO_TPU_METACACHE") is True      # default
    with pytest.raises(KeyError):
        knobs.get_int("MINIO_TPU_NOT_A_KNOB")
    with pytest.raises(KeyError):
        knobs.get_raw("MINIO_TPU_NOT_A_KNOB")


def test_knob_table_covers_registry_and_readme_is_fresh():
    table = knobs.render_table()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table
    # committed README must match the registry (the drift gate)
    assert knobtable.check_drift() == []


def test_knob_drift_detected(tmp_path, monkeypatch):
    readme = tmp_path / "README.md"
    readme.write_text("# x\n\nno markers here\n")
    monkeypatch.setattr(knobtable, "README", str(readme))
    vs = knobtable.check_drift()
    assert vs and "markers missing" in vs[0].message
    mod = knobtable.load_knobs()
    readme.write_text(
        f"# x\n\n{mod.TABLE_BEGIN}\nstale table\n{mod.TABLE_END}\n")
    vs2 = knobtable.check_drift()
    assert vs2 and "drifted" in vs2[0].message


# ---------------------------------------------------------------------------
# the runner (CI gate)
# ---------------------------------------------------------------------------

def test_runner_exits_zero_on_tree(capsys, tmp_path):
    """THE smoke pin: the committed tree is lint-clean, so the gate
    can't rot into a permanently-red (ignored) state."""
    report = tmp_path / "check.json"
    assert check_run.main(["--json", str(report)]) == 0
    doc = json.loads(report.read_text())
    assert doc["gate"] == "pass"
    assert doc["violations"] == []
    assert doc["files_scanned"] > 100


def test_runner_single_rule_and_json_stdout(capsys):
    assert check_run.main(["--rule", "error-map", "--json", "-"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out[:out.rindex("}") + 1])
    assert doc["gate"] == "pass"


def test_replication_chain_rule():
    """The hook-coverage rule proves every mutation verb reaches the
    replication queue: feed -> attach_replication ->
    plane.on_namespace_change -> cluster wiring. Breaking any link
    fires; the real tree is green (covered by
    test_hook_rule_green_on_real_tree)."""
    ok_engine = [_src("minio_tpu/object/engine.py", ENGINE_OK),
                 _src("minio_tpu/object/multipart.py", MULTIPART_OK)]
    ss_ok = '''
class ErasureServerSets:
    def attach_replication(self, plane):
        self.replication = plane
        self.register_namespace_listener(plane.on_namespace_change)
'''
    plane_ok = '''
class ReplicationPlane:
    def on_namespace_change(self, bucket, key):
        pass
'''
    cluster_ok = '''
def boot(layer, plane):
    layer.attach_replication(plane)
'''
    full = ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py", plane_ok),
        _src("minio_tpu/cluster.py", cluster_ok)]
    assert rules_project.check_hook_coverage(full) == []

    # attach loses its register call -> flagged
    vs = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", '''
class ErasureServerSets:
    def attach_replication(self, plane):
        self.replication = plane
'''),
        _src("minio_tpu/replicate/plane.py", plane_ok),
        _src("minio_tpu/cluster.py", cluster_ok)])
    assert any("register_namespace_listener" in v.message for v in vs)

    # the plane loses its listener method -> flagged
    vs2 = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py",
             "class ReplicationPlane:\n    pass\n"),
        _src("minio_tpu/cluster.py", cluster_ok)])
    assert any("on_namespace_change() missing" in v.message for v in vs2)

    # cluster boot forgets to attach -> flagged
    vs3 = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py", plane_ok),
        _src("minio_tpu/cluster.py", "def boot(layer):\n    pass\n")])
    assert any("never calls attach_replication" in v.message
               for v in vs3)


def test_notify_chain_rule():
    """The hook-coverage rule proves every mutation verb reaches
    bucket event notification: feed -> attach_notifications ->
    NotificationPlane.on_namespace_change -> cluster wiring. Breaking
    any link fires; absent the notify plane module the chain is out of
    scope (so fixture trees above stay green)."""
    ok_engine = [_src("minio_tpu/object/engine.py", ENGINE_OK),
                 _src("minio_tpu/object/multipart.py", MULTIPART_OK)]
    ss_ok = '''
class ErasureServerSets:
    def attach_replication(self, plane):
        self.replication = plane
        self.register_namespace_listener(plane.on_namespace_change)
    def attach_notifications(self, plane):
        self.notifications = plane
        self.register_namespace_listener(plane.on_namespace_change)
'''
    repl_plane_ok = '''
class ReplicationPlane:
    def on_namespace_change(self, bucket, key):
        pass
'''
    notify_plane_ok = '''
class NotificationPlane:
    def on_namespace_change(self, bucket, key):
        pass
'''
    cluster_ok = '''
def boot(layer, repl, notify):
    layer.attach_replication(repl)
    layer.attach_notifications(notify)
'''
    full = ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py", repl_plane_ok),
        _src("minio_tpu/notify/plane.py", notify_plane_ok),
        _src("minio_tpu/cluster.py", cluster_ok)]
    assert rules_project.check_hook_coverage(full) == []

    # attach_notifications loses its register call -> flagged
    vs = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", '''
class ErasureServerSets:
    def attach_replication(self, plane):
        self.replication = plane
        self.register_namespace_listener(plane.on_namespace_change)
    def attach_notifications(self, plane):
        self.notifications = plane
'''),
        _src("minio_tpu/replicate/plane.py", repl_plane_ok),
        _src("minio_tpu/notify/plane.py", notify_plane_ok),
        _src("minio_tpu/cluster.py", cluster_ok)])
    assert any("attach_notifications() never calls "
               "register_namespace_listener" in v.message for v in vs)

    # attach_notifications gone entirely -> flagged
    vs1 = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", '''
class ErasureServerSets:
    def attach_replication(self, plane):
        self.replication = plane
        self.register_namespace_listener(plane.on_namespace_change)
'''),
        _src("minio_tpu/replicate/plane.py", repl_plane_ok),
        _src("minio_tpu/notify/plane.py", notify_plane_ok),
        _src("minio_tpu/cluster.py", cluster_ok)])
    assert any("attach_notifications() missing" in v.message
               for v in vs1)

    # the plane loses its listener method -> flagged
    vs2 = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py", repl_plane_ok),
        _src("minio_tpu/notify/plane.py",
             "class NotificationPlane:\n    pass\n"),
        _src("minio_tpu/cluster.py", cluster_ok)])
    assert any("NotificationPlane.on_namespace_change() missing"
               in v.message for v in vs2)

    # cluster boot forgets to attach -> flagged
    vs3 = rules_project.check_hook_coverage(ok_engine + [
        _src("minio_tpu/object/server_sets.py", ss_ok),
        _src("minio_tpu/replicate/plane.py", repl_plane_ok),
        _src("minio_tpu/notify/plane.py", notify_plane_ok),
        _src("minio_tpu/cluster.py", '''
def boot(layer, repl):
    layer.attach_replication(repl)
''')])
    assert any("never calls attach_notifications" in v.message
               for v in vs3)


# ---------------------------------------------------------------------------
# rule: admission
# ---------------------------------------------------------------------------

BAD_SHED = '''
from minio_tpu.s3.s3errors import S3Error
from minio_tpu.utils import telemetry
def shed(self, ctx):
    telemetry.REGISTRY.counter(
        "minio_tpu_requests_shed_total",
        "Requests shed").inc(reason="ad-hoc")
    raise S3Error("SlowDown", "go away")
'''


def test_admission_rule_fires_on_stray_shed():
    """A SlowDown decision or a requests_shed_total reference outside
    the AdmissionController module is an error (migrating the
    handlers' original shed window is what proved this fires)."""
    vs = rules_ast.check_admission(
        [_src("minio_tpu/s3/handlers.py", BAD_SHED)])
    msgs = "\n".join(v.message for v in vs)
    assert "S3Error(\"SlowDown\")" in msgs
    assert "requests_shed_total" in msgs
    assert len(vs) == 2


def test_admission_rule_quiet_in_controller_and_on_tree():
    # the controller module itself is the ONE exempt home
    assert rules_ast.check_admission(
        [_src("minio_tpu/s3/edge/admission.py", BAD_SHED)]) == []
    # the committed tree is clean: the handlers' shed window migrated
    from check.core import load_sources
    assert rules_ast.check_admission(load_sources()) == []


BAD_PROBE = '''
from minio_tpu.utils.bandwidth import TokenBucket
bucket = TokenBucket(10.0, 10.0)
def maybe_throttle(ctx):
    if bucket.try_take(1):
        return
    wait = bucket.peek(ctx.content_length)
    ctx.respond(503, retry_after=wait)
'''


def test_admission_rule_fires_on_stray_budget_probe():
    """A TokenBucket admission probe (try_take / peek with an amount)
    outside the admission/QoS plane is a private refusal path in the
    making — the rule catches the probe itself, before anyone wires
    it to a 503 (ISSUE 19 satellite)."""
    vs = rules_ast.check_admission(
        [_src("minio_tpu/object/engine.py", BAD_PROBE)])
    msgs = "\n".join(v.message for v in vs)
    assert "budget probe outside the admission/QoS plane" in msgs
    assert len(vs) == 2                # try_take AND peek both flagged


def test_admission_rule_budget_probe_quiet_in_qos_plane():
    # the three modules that ARE the plane may probe freely
    for home in ("minio_tpu/s3/edge/admission.py",
                 "minio_tpu/s3/qos.py",
                 "minio_tpu/utils/bandwidth.py"):
        assert rules_ast.check_admission([_src(home, BAD_PROBE)]) == []
    # zero-argument .peek() calls (the s3select parser's lookahead)
    # are NOT budget probes and stay quiet anywhere
    lookahead = "def parse(tok):\n    return tok.peek()\n"
    assert rules_ast.check_admission(
        [_src("minio_tpu/s3select/sql.py", lookahead)]) == []


# ---------------------------------------------------------------------------
# rule: metrics-hygiene / label cardinality (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

BAD_CARDINALITY = '''
from ...utils import telemetry
_OPS = telemetry.REGISTRY.counter("minio_tpu_zz_ops_total", "ops")
def hot(self, bucket, key, oi):
    _OPS.inc(bucket=bucket)
    _OPS.inc(verb=key)
    telemetry.REGISTRY.histogram(
        "minio_tpu_zz_seconds", "lat").observe(0.1, target=oi.name)
'''

GOOD_CARDINALITY = '''
from ...utils import telemetry
_OPS = telemetry.REGISTRY.counter("minio_tpu_zz_ops_total", "ops")
def hot(self, verb, reason):
    _OPS.inc(verb=verb)
    _OPS.inc(reason=reason)
    _OPS.inc(stage="compute")
    _OPS.inc(path="fallback")        # constant value: bounded
'''


def test_label_cardinality_fires_in_hot_modules():
    """Raw bucket/object/key names as metric label values in hot-path
    modules are unbounded cardinality: the key form (bucket=...), the
    value form (verb=key) and the attribute form (target=oi.name) all
    fire."""
    vs = rules_ast.check_label_cardinality(
        [_src("minio_tpu/object/engine.py", BAD_CARDINALITY)])
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3, vs
    assert "request-derived 'bucket'" in msgs
    assert "`key`" in msgs
    assert "`oi.name`" in msgs


ALIAS_CARDINALITY = '''
from ...utils import telemetry
g = telemetry.REGISTRY.gauge
def hot(self, bucket):
    g("minio_tpu_zz_depth", "d").set(1, bucket=bucket)
'''


def test_label_cardinality_sees_aliased_getters():
    """`g = REGISTRY.gauge; g("n").set(..., bucket=b)` must fire too —
    the attribute-only scan's blind spot (review finding)."""
    vs = rules_ast.check_label_cardinality(
        [_src("minio_tpu/object/engine.py", ALIAS_CARDINALITY)])
    assert len(vs) == 1 and "request-derived 'bucket'" in vs[0].message


def test_label_cardinality_quiet_on_bounded_and_cold_modules():
    # bounded vocabularies (verb/reason/stage + constants) stay clean
    assert rules_ast.check_label_cardinality(
        [_src("minio_tpu/object/engine.py", GOOD_CARDINALITY)]) == []
    # the same bad code OUTSIDE a hot-path module is tolerated (the
    # admin handler's per-bucket usage gauges refresh at exposition
    # time and clear() on every scrape)
    assert rules_ast.check_label_cardinality(
        [_src("minio_tpu/s3/admin.py", BAD_CARDINALITY)]) == []
    # the committed tree argues every hot-path label bounded
    from check.core import load_sources
    assert rules_ast.check_label_cardinality(load_sources()) == []


# ---------------------------------------------------------------------------
# README metrics table (generated; drift gated)
# ---------------------------------------------------------------------------

def test_metrics_table_covers_registry_and_readme_is_fresh():
    from check import metricstable
    fams = metricstable.collect_families()
    # the core families the telemetry plane registers must be seen by
    # the static scan (registration sites, not a live render)
    for fam in ("minio_tpu_http_requests_duration_seconds",
                "minio_tpu_device_dispatch_seconds",
                "minio_tpu_requests_shed_total",
                "minio_tpu_cluster_scrape_failed_total",
                "minio_tpu_edge_loop_lag_seconds",
                # registered through getter ALIASES (g = REGISTRY.gauge)
                # — the attribute-only scan's blind spot, found in
                # review: the table must see these too
                "minio_tpu_edge_pool_busy",
                "minio_disks_online"):
        assert fam in fams, fam
    table = metricstable.render_table()
    for fam in fams:
        assert fam in table
    # committed README is fresh (the gate would fail otherwise)
    assert metricstable.check_drift() == []


def test_metrics_table_drift_detected(monkeypatch, tmp_path):
    from check import metricstable
    stale = tmp_path / "README.md"
    with open(metricstable.README, encoding="utf-8") as f:
        text = f.read()
    stale.write_text(text.replace(
        "| counter |", "| gauge |", 1), encoding="utf-8")
    monkeypatch.setattr(metricstable, "README", str(stale))
    vs = metricstable.check_drift()
    assert vs and "drifted" in vs[0].message


# ---------------------------------------------------------------------------
# rule: crashpoint
# ---------------------------------------------------------------------------

BAD_COMMIT = '''
class Store:
    def commit(self, d):
        # write + rename across >= 2 paths, no crashpoint declared
        d.write_all("v", "xl.meta", b"m")
        d.rename_data("tmp", "t", "dd", "b", "o")

    def save_everywhere(self, pools, payload):
        for z in pools:
            z.put_object(".minio.sys", "doc.json", payload)
'''

GOOD_COMMIT = '''
from ..utils import crashpoint

class Store:
    def commit(self, d):
        d.write_all("v", "xl.meta", b"m")
        crashpoint.hit("put.meta.before_rename")
        d.rename_data("tmp", "t", "dd", "b", "o")

    def save_everywhere(self, pools, payload):
        for z in pools:
            crashpoint.hit("topology.save.pool")
            z.put_object(".minio.sys", "doc.json", payload)

    def single_write(self, d):
        d.write_all("v", "doc.json", b"x")      # one path: no window

    def read_side(self, d):
        return d.read_all("v", "doc.json")
'''

BAD_HIT_NAMES = '''
from ..utils import crashpoint

def f(name):
    crashpoint.hit("not.a.registered.point")
    crashpoint.hit(name)
'''


def _crash_registered():
    from check import crashtable
    return set(crashtable.load_crashpoints().CRASHPOINTS)


def test_crashpoint_rule_fires_on_uncovered_commit_windows():
    src = _src("minio_tpu/object/topology.py", BAD_COMMIT)
    vs = rules_project.check_crashpoint([src], _crash_registered())
    msgs = [v.message for v in vs]
    assert len(vs) == 2
    assert any("commit" in m and "write" in m for m in msgs)
    assert any("loop" in m or "persistence" in m for m in msgs)


def test_crashpoint_rule_quiet_on_declared_and_cold_modules():
    good = _src("minio_tpu/object/topology.py", GOOD_COMMIT)
    assert rules_project.check_crashpoint([good],
                                          _crash_registered()) == []
    # same bad shape OUTSIDE the designated commit modules: quiet
    cold = _src("minio_tpu/s3/handlers.py", BAD_COMMIT)
    assert rules_project.check_crashpoint([cold],
                                          _crash_registered()) == []


def test_crashpoint_rule_flags_bad_hit_names_everywhere():
    src = _src("minio_tpu/s3/handlers.py", BAD_HIT_NAMES)
    vs = rules_project.check_crashpoint([src], _crash_registered())
    assert len(vs) == 2
    assert any("unregistered" in v.message for v in vs)
    assert any("constant" in v.message for v in vs)


def test_crashpoint_rule_suppression():
    suppressed = BAD_COMMIT.replace(
        "    def commit(self, d):",
        "    # check: allow(crashpoint) two-phase handled by caller\n"
        "    def commit(self, d):")
    src = _src("minio_tpu/object/topology.py", suppressed)
    from check.core import filter_allowed
    vs = filter_allowed(src, rules_project.check_crashpoint(
        [src], _crash_registered()))
    assert len(vs) == 1          # only save_everywhere still flagged


def test_crashpoint_rule_green_on_real_tree():
    from check.core import load_sources, filter_allowed
    sources = load_sources()
    by_rel = {s.rel: s for s in sources}
    vs = rules_project.check_crashpoint(sources, _crash_registered())
    out = []
    for v in vs:
        src = by_rel.get(v.path)
        if src is None or not src.is_allowed(v.rule, v.line):
            out.append(v)
    assert out == [], [str(v) for v in out]


def test_crashpoint_table_covers_registry_and_readme_is_fresh():
    from check import crashtable
    mod = crashtable.load_crashpoints()
    table = mod.render_table()
    for name in mod.CRASHPOINTS:
        assert f"`{name}`" in table
    assert crashtable.check_drift() == []


def test_crashpoint_table_drift_detected(tmp_path, monkeypatch):
    from check import crashtable
    readme = tmp_path / "README.md"
    readme.write_text("# x\n\nno markers\n")
    monkeypatch.setattr(crashtable, "README", str(readme))
    vs = crashtable.check_drift()
    assert vs and "markers missing" in vs[0].message
    mod = crashtable.load_crashpoints()
    readme.write_text(
        f"# x\n\n{mod.TABLE_BEGIN}\nstale\n{mod.TABLE_END}\n")
    vs2 = crashtable.check_drift()
    assert vs2 and "drifted" in vs2[0].message


# ---------------------------------------------------------------------------
# rule: deadline (ISSUE 15 satellite — gray-failure plane)
# ---------------------------------------------------------------------------

BAD_DEADLINE = '''
def fan_out(self, futs, sock):
    for f in futs:
        f.result()
    return sock.recv(4096)
'''

GOOD_DEADLINE = '''
def fan_out(self, futs, sock):
    for f in futs:
        f.result(timeout=5.0)
    out = [f.result(2.0) for f in futs]
    # check: allow(deadline) bounded by the hedged reader's own deadline
    out.append(futs[0].result())
    return out
'''


def test_deadline_rule_fires_on_bare_waits():
    vs = rules_ast.check_deadline(
        [_src("minio_tpu/object/engine.py", BAD_DEADLINE)])
    msgs = "\n".join(v.message for v in vs)
    assert "bare unbounded future .result()" in msgs
    assert ".recv()" in msgs
    assert len(vs) == 2


def test_deadline_rule_quiet_on_bounded_and_cold_modules():
    from check.core import filter_allowed
    src = _src("minio_tpu/object/engine.py", GOOD_DEADLINE)
    # timeout args are clean; the bare one carries its allow() argument
    assert filter_allowed(src, rules_ast.check_deadline([src])) == []
    # a module outside the hot list is not scanned at all
    assert rules_ast.check_deadline(
        [_src("minio_tpu/utils/telemetry.py", BAD_DEADLINE)]) == []


def test_deadline_rule_clean_on_tree():
    """Every hot-path fan-out in the committed tree either carries a
    timeout, rides the hedged reader / quorum lane, or argues its
    bound inline — the satellite's deliverable."""
    from check.core import filter_allowed, load_sources
    sources = load_sources()
    by_rel = {s.rel: s for s in sources}
    vs = rules_ast.check_deadline(sources)
    left = []
    for v in vs:
        src = by_rel.get(v.path)
        left.extend(filter_allowed(src, [v]) if src else [v])
    assert left == []


# ---------------------------------------------------------------------------
# rule: crypto-hygiene
# ---------------------------------------------------------------------------

BAD_CRYPTO = '''
from ..ops import chacha20_ref
from ..ops.chacha20_ref import tag_detached
from ..features.crypto import _pkg_nonce

def rogue_nonce(base, seq):
    # hand-rolled seq mixing: the exact bug class the rule forbids
    return _pkg_nonce(base, seq)

def rogue_tag(key, nonce, aad, ct):
    return tag_detached(key, nonce, aad, ct)

def rogue_xor(data, key, nonce):
    return chacha20_ref.xor_stream(data, key, nonce)
'''

GOOD_CRYPTO = '''
from ..features import crypto as sse

def fine(oek, base, data):
    enc = sse.ChaChaEncryptor(oek, base)
    return enc.update(data) + enc.finalize()
'''


def test_crypto_hygiene_fires_on_rogue_primitive_use():
    vs = rules_project.check_crypto_hygiene(
        [_src("minio_tpu/s3/handlers.py", BAD_CRYPTO)])
    msgs = "\n".join(v.message for v in vs)
    assert "chacha20_ref" in msgs
    assert "_pkg_nonce" in msgs or "tag_detached" in msgs
    # 3 rogue imports + 3 rogue calls
    assert len(vs) >= 5


def test_crypto_hygiene_quiet_on_owner_and_consumers():
    # the owner derives nonces and drives the AEAD reference freely
    assert rules_project.check_crypto_hygiene(
        [_src("minio_tpu/features/crypto.py", BAD_CRYPTO)]) == []
    # the fused device programs may import the jax kernels (keystream
    # over nonce arrays crypto.py already derived)
    assert rules_project.check_crypto_hygiene(
        [_src("minio_tpu/models/pipeline.py",
              "from ..ops import chacha20_jax\n")]) == []
    # high-level transform consumers are clean
    assert rules_project.check_crypto_hygiene(
        [_src("minio_tpu/s3/handlers.py", GOOD_CRYPTO)]) == []


def test_crypto_hygiene_clean_on_tree():
    """Package nonces are derived only inside features/crypto.py in the
    committed tree — the satellite's deliverable."""
    from check.core import filter_allowed, load_sources
    sources = load_sources()
    by_rel = {s.rel: s for s in sources}
    vs = rules_project.check_crypto_hygiene(sources)
    left = []
    for v in vs:
        src = by_rel.get(v.path)
        left.extend(filter_allowed(src, [v]) if src else [v])
    assert left == []
