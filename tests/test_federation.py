"""Bucket federation over etcd DNS (VERDICT r3 missing #4; reference
cmd/etcd.go + cmd/config/dns + the bucket-forwarding middleware at
cmd/routers.go:46): etcd v3 KV client against an in-process fake,
CoreDNS-layout record CRUD, and two live S3 "clusters" transparently
serving each other's buckets with client signatures intact."""

from __future__ import annotations

import base64
import http.server
import json
import threading

import pytest

from minio_tpu.distributed.etcd import EtcdClient, EtcdError
from minio_tpu.features.federation import BucketFederation
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3.server import S3Server
from tests.test_s3 import CREDS, REGION, S3TestClient


class FakeEtcd(http.server.BaseHTTPRequestHandler):
    """etcd v3 JSON gateway subset: kv/put, kv/range (point + prefix),
    kv/deleterange."""

    store: dict = {}
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0) or 0)
        try:
            req = json.loads(self.rfile.read(n) or b"{}")
        except ValueError:
            return self._reply(400, {})
        key = base64.b64decode(req.get("key", "")).decode()
        range_end = base64.b64decode(req.get("range_end", "")).decode()
        if self.path == "/v3/kv/put":
            self.store[key] = base64.b64decode(req.get("value", ""))
            return self._reply(200, {})
        if self.path == "/v3/kv/range":
            if range_end:
                kvs = [(k, v) for k, v in sorted(self.store.items())
                       if key <= k < range_end]
            else:
                kvs = [(key, self.store[key])] if key in self.store \
                    else []
            return self._reply(200, {"kvs": [
                {"key": base64.b64encode(k.encode()).decode(),
                 "value": base64.b64encode(v).decode()}
                for k, v in kvs]})
        if self.path == "/v3/kv/deleterange":
            if range_end:
                for k in [k for k in self.store
                          if key <= k < range_end]:
                    del self.store[k]
            else:
                self.store.pop(key, None)
            return self._reply(200, {})
        return self._reply(404, {})

    def _reply(self, status, payload):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def etcd_server():
    FakeEtcd.store = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeEtcd)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_etcd_kv_client(etcd_server):
    c = EtcdClient(f"http://127.0.0.1:{etcd_server}")
    assert c.get("missing") is None
    c.put("a/b/one", b"1")
    c.put("a/b/two", b"2")
    c.put("a/c", b"3")
    assert c.get("a/b/one") == b"1"
    assert c.get_prefix("a/b/") == {"a/b/one": b"1", "a/b/two": b"2"}
    c.delete("a/b/one")
    assert c.get("a/b/one") is None
    c.delete_prefix("a/")
    assert c.get_prefix("a/") == {}
    with pytest.raises(EtcdError, match="unreachable"):
        EtcdClient("http://127.0.0.1:1", timeout=0.4).get("x")
    with pytest.raises(ValueError):
        EtcdClient("not-a-url")


def _cluster(tmp_path, name, etcd_port, domain="fed.example.com"):
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"{name}-d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    fed = BucketFederation(EtcdClient(f"http://127.0.0.1:{etcd_port}"),
                           domain, "127.0.0.1", srv.port)
    srv.api.federation = fed
    return srv, sets, fed


def test_dns_record_layout(etcd_server, tmp_path):
    """Records land in the CoreDNS/skydns layout the reference writes,
    so a real CoreDNS on the same etcd would resolve bucket.domain."""
    srv, sets, fed = _cluster(tmp_path, "a", etcd_server)
    try:
        c = S3TestClient("127.0.0.1", srv.port)
        assert c.request("PUT", "/fedbucket")[0] == 200
        key = (f"/skydns/com/example/fed/fedbucket/"
               f"127.0.0.1:{srv.port}")
        assert key in FakeEtcd.store
        rec = json.loads(FakeEtcd.store[key])
        assert rec["host"] == "127.0.0.1" and rec["port"] == srv.port
        assert fed.list_buckets() == ["fedbucket"]
        assert c.request("DELETE", "/fedbucket")[0] == 204
        assert key not in FakeEtcd.store
    finally:
        srv.stop()
        sets.close()


def test_multinode_records_and_startup_sweep(etcd_server, tmp_path):
    """Review r4: records are written for EVERY node of the owning
    cluster and unregister clears them all (a DELETE handled by node 2
    must not leave node 1's record stale); register_existing publishes
    buckets that predate federation."""
    c = EtcdClient(f"http://127.0.0.1:{etcd_server}")
    fed = BucketFederation(c, "fed.example.com", "10.0.0.1", 9000,
                           cluster_addrs=[("10.0.0.1", 9000),
                                          ("10.0.0.2", 9000)])
    fed.register("multi")
    assert len(fed.lookup("multi")) == 2
    # a sibling node's federation object (same cluster_addrs) sees the
    # bucket as its own
    sib = BucketFederation(c, "fed.example.com", "10.0.0.2", 9000,
                           cluster_addrs=[("10.0.0.1", 9000),
                                          ("10.0.0.2", 9000)])
    assert sib.owner_of("multi") is None
    # unregister from the OTHER node removes both records
    sib.unregister("multi")
    assert fed.lookup("multi") == []

    # startup sweep: pre-existing buckets get published
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"sw-d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    try:
        sets.make_bucket("preexisting")
        fed.register_existing(sets)
        assert "preexisting" in fed.list_buckets()
    finally:
        sets.close()


def test_cross_cluster_forwarding(etcd_server, tmp_path):
    """A bucket owned by cluster A serves through cluster B: B's
    router forwards the raw request to A (shared creds, signature
    verified at the owner), responses stream back. Unknown buckets
    still 404, and A's own requests never loop."""
    a_srv, a_sets, _ = _cluster(tmp_path, "a", etcd_server)
    b_srv, b_sets, _ = _cluster(tmp_path, "b", etcd_server)
    try:
        ca = S3TestClient("127.0.0.1", a_srv.port)
        cb = S3TestClient("127.0.0.1", b_srv.port)
        assert ca.request("PUT", "/abucket")[0] == 200
        payload = b"federated-payload" * 1000
        assert ca.request("PUT", "/abucket/obj",
                          body=payload)[0] == 200

        # read A's object THROUGH B
        st, _, got = cb.request("GET", "/abucket/obj")
        assert st == 200 and got == payload
        # write through B lands on A
        assert cb.request("PUT", "/abucket/via-b",
                          body=b"hello-a")[0] == 200
        st, _, got = ca.request("GET", "/abucket/via-b")
        assert st == 200 and got == b"hello-a"
        # listing through B sees both
        st, _, body = cb.request("GET", "/abucket")
        assert st == 200 and b"via-b" in body
        # delete through B
        assert cb.request("DELETE", "/abucket/via-b")[0] == 204
        assert ca.request("GET", "/abucket/via-b")[0] == 404

        # a bucket in NO cluster is still NoSuchBucket on both
        assert cb.request("GET", "/ghostbucket/x")[0] == 404
        assert ca.request("GET", "/ghostbucket/x")[0] == 404

        # ListBuckets on B merges the federation's bucket names
        st, _, body = cb.request("GET", "/")
        assert st == 200 and b"abucket" in body

        # B's own buckets serve locally even with federation on
        assert cb.request("PUT", "/bbucket")[0] == 200
        assert cb.request("PUT", "/bbucket/o", body=b"local")[0] == 200
        st, _, got = cb.request("GET", "/bbucket/o")
        assert st == 200 and got == b"local"
        # ... and A forwards to B for it
        st, _, got = ca.request("GET", "/bbucket/o")
        assert st == 200 and got == b"local"
    finally:
        a_srv.stop()
        b_srv.stop()
        a_sets.close()
        b_sets.close()


def test_forwarding_survives_etcd_and_owner_outage(etcd_server,
                                                   tmp_path):
    """etcd down: local buckets keep serving (federation degrades to
    local-only). Owner down: the forwarder answers 503, not a hang."""
    a_srv, a_sets, _ = _cluster(tmp_path, "a", etcd_server)
    b_srv, b_sets, b_fed = _cluster(tmp_path, "b", etcd_server)
    try:
        ca = S3TestClient("127.0.0.1", a_srv.port)
        cb = S3TestClient("127.0.0.1", b_srv.port)
        assert ca.request("PUT", "/abucket2")[0] == 200
        assert cb.request("PUT", "/blocal")[0] == 200

        # owner A goes down: forwarding from B reports 503
        a_srv.stop()
        b_fed.timeout = 1.0
        st, _, _ = cb.request("GET", "/abucket2/x")
        assert st == 503

        # etcd down: B's local bucket still serves
        b_fed.etcd = EtcdClient("http://127.0.0.1:1", timeout=0.4)
        assert cb.request("PUT", "/blocal/o", body=b"v")[0] == 200
        st, _, got = cb.request("GET", "/blocal/o")
        assert st == 200 and got == b"v"
        # unknown bucket with etcd down: NoSuchBucket, not 500
        assert cb.request("GET", "/abucket2/x")[0] == 404
    finally:
        b_srv.stop()
        a_sets.close()
        b_sets.close()


# ---------------------------------------------------------------------------
# IAM over etcd (cmd/iam-etcd-store.go): one identity plane for the
# whole federation
# ---------------------------------------------------------------------------

def test_iam_etcd_store_roundtrip(etcd_server):
    """IAMSys over the etcd store: CRUD + reload + per-entity deltas
    behave exactly as over the object store, including the
    percent-encoded federated-subject filenames."""
    from minio_tpu.iam.store import EtcdIAMStore, IAMStoreError
    from minio_tpu.iam.sys import IAMSys
    from tests.test_s3 import CREDS

    store = EtcdIAMStore(EtcdClient(f"http://127.0.0.1:{etcd_server}"))
    iam = IAMSys(root_cred=CREDS, store=store)
    iam.add_user("euser", "esecret12345")
    iam.attach_policy("readwrite", user="euser")
    iam.add_members_to_group("eg", ["euser"])
    iam.assume_role_with_claims("oidc:a/b", ["readonly"])

    # a second IAMSys over the same etcd sees everything
    iam2 = IAMSys(root_cred=CREDS,
                  store=EtcdIAMStore(
                      EtcdClient(f"http://127.0.0.1:{etcd_server}")))
    assert iam2.get_credentials("euser").secret_key == "esecret12345"
    assert iam2.user_policy["euser"] == ["readwrite"]
    assert "euser" in iam2.groups["eg"]["members"]
    assert iam2.user_policy["oidc:a/b"] == ["readonly"]

    # per-entity delta against the etcd store
    iam.add_user("deltau", "deltasecret1")
    iam2.apply_delta("user", "deltau")
    assert iam2.get_credentials("deltau") is not None
    iam.remove_user("deltau")
    iam2.apply_delta("user", "deltau")
    assert iam2.get_credentials("deltau") is None

    # transient etcd failure must NOT read as deletion
    iam2.store = EtcdIAMStore(EtcdClient("http://127.0.0.1:1",
                                         timeout=0.4))
    iam2.apply_delta("user", "euser")
    assert iam2.get_credentials("euser") is not None
    import pytest as _pytest
    with _pytest.raises(IAMStoreError):
        iam2.store.read_one("users", "euser")


def test_federated_clusters_share_iam(etcd_server, tmp_path):
    """A user created on cluster A authenticates against cluster B:
    both IAMs read the same etcd store (the reference's federated
    deployments share IAM via etcd)."""
    from minio_tpu.iam.store import EtcdIAMStore
    from minio_tpu.iam.sys import IAMSys

    def cluster_with_iam(name):
        sets = ErasureSets.from_drives(
            [str(tmp_path / f"{name}-d{i}") for i in range(4)], 1, 4, 2,
            block_size=1 << 16)
        iam = IAMSys(root_cred=CREDS, store=EtcdIAMStore(
            EtcdClient(f"http://127.0.0.1:{etcd_server}")))
        srv = S3Server(sets, creds=CREDS, region=REGION,
                       iam=iam).start()
        return srv, sets, iam

    a_srv, a_sets, a_iam = cluster_with_iam("ia")
    b_srv, b_sets, b_iam = cluster_with_iam("ib")
    try:
        a_iam.add_user("sharedu", "sharedsecret1")
        a_iam.attach_policy("readwrite", user="sharedu")
        b_iam.load()        # the refresh loop's job in production

        from minio_tpu.s3.credentials import Credentials
        from tests.test_s3 import S3TestClient
        cb = S3TestClient("127.0.0.1", b_srv.port,
                          creds=Credentials("sharedu", "sharedsecret1"))
        assert cb.request("PUT", "/sharedbucket")[0] == 200
        assert cb.request("PUT", "/sharedbucket/o",
                          body=b"cross-iam")[0] == 200
        st, _, got = cb.request("GET", "/sharedbucket/o")
        assert st == 200 and got == b"cross-iam"
    finally:
        a_srv.stop()
        b_srv.stop()
        a_sets.close()
        b_sets.close()


def test_iam_migration_partial_seed_recovery(etcd_server, tmp_path):
    """ADVICE r4: a seed that dies partway must NOT leave an etcd store
    the next boot adopts as authoritative — without the seed-complete
    marker the migration re-seeds the missing records instead of
    silently dropping every identity only the old store held."""
    from minio_tpu.iam.store import EtcdIAMStore, IAMStoreError
    from minio_tpu.iam.sys import IAMSys

    class DiesAfter(EtcdIAMStore):
        """Store that fails after `budget` saves (mid-seed crash)."""

        def __init__(self, etcd, budget):
            super().__init__(etcd)
            self.budget = budget

        def save(self, path, payload):
            if self.budget <= 0:
                raise IAMStoreError("injected: etcd gone")
            self.budget -= 1
            super().save(path, payload)

    sets = ErasureSets.from_drives(
        [str(tmp_path / f"pseed-d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    try:
        iam = IAMSys(sets, root_cred=CREDS)
        for i in range(4):
            iam.add_user(f"user{i}", f"user{i}-secret-long")
        iam.attach_policy("readonly", user="user0")
        old_store = iam.store

        # seed dies after 2 saves: partial etcd content, NO marker
        url = f"http://127.0.0.1:{etcd_server}"
        dying = DiesAfter(EtcdClient(url), budget=2)
        iam.migrate_to_store(dying)
        assert iam.store is old_store          # fell back
        assert iam.get_credentials("user3") is not None
        live = EtcdIAMStore(EtcdClient(url))
        assert live.read_one("format", "seed-complete") is None
        assert live.read_all("users")          # partial content exists

        # a user deleted (durably, in the old store) between the
        # attempts must NOT be resurrected by the crashed seed's
        # leftovers in etcd (review r5: unmarked target is scratch)
        seeded_names = {k for k in live.read_all("users")}
        victim = sorted(n for n in seeded_names if n != "user0")[0]
        iam.remove_user(victim)

        # next migration: partial store is NOT authoritative — it
        # re-seeds from the current cache and writes the marker
        iam.migrate_to_store(live)
        assert iam.store is live
        assert live.read_one("format", "seed-complete")
        for i in range(4):
            name = f"user{i}"
            want_alive = name != victim
            assert (iam.get_credentials(name) is not None) == want_alive
        fresh = IAMSys(root_cred=CREDS, store=EtcdIAMStore(
            EtcdClient(url)))
        assert fresh.get_credentials(victim) is None, \
            "crashed-seed leftover resurrected a deleted identity"
        for i in range(4):
            name = f"user{i}"
            if name != victim:
                assert fresh.get_credentials(name) is not None
        assert fresh.user_policy["user0"] == ["readonly"]
    finally:
        sets.close()


def test_iam_migration_to_etcd(etcd_server, tmp_path):
    """Review r4: switching to the etcd store must carry existing
    identities over (empty target is seeded), and a populated target
    is authoritative; an unreachable target keeps the old store."""
    from minio_tpu.iam.store import EtcdIAMStore
    from minio_tpu.iam.sys import IAMSys
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"mig-d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    try:
        iam = IAMSys(sets, root_cred=CREDS)
        iam.add_user("premig", "premigsecret1")
        iam.attach_policy("readonly", user="premig")

        # unreachable etcd: store unchanged, identities intact
        dead = EtcdIAMStore(EtcdClient("http://127.0.0.1:1",
                                       timeout=0.4))
        old_store = iam.store
        iam.migrate_to_store(dead)
        assert iam.store is old_store
        assert iam.get_credentials("premig") is not None

        # live empty etcd: seeded from the object store
        live = EtcdIAMStore(EtcdClient(f"http://127.0.0.1:{etcd_server}"))
        iam.migrate_to_store(live)
        assert iam.store is live
        assert iam.get_credentials("premig").secret_key == \
            "premigsecret1"
        # a fresh IAM over etcd sees the migrated identities
        other = IAMSys(root_cred=CREDS, store=EtcdIAMStore(
            EtcdClient(f"http://127.0.0.1:{etcd_server}")))
        assert other.get_credentials("premig") is not None
        assert other.user_policy["premig"] == ["readonly"]

        # populated target is authoritative: a second cluster joining
        # does NOT overwrite it with its own (different) local users
        sets2 = ErasureSets.from_drives(
            [str(tmp_path / f"mig2-d{i}") for i in range(4)], 1, 4, 2,
            block_size=1 << 16)
        try:
            iam2 = IAMSys(sets2, root_cred=CREDS)
            iam2.add_user("localonly", "localsecret12")
            iam2.migrate_to_store(EtcdIAMStore(
                EtcdClient(f"http://127.0.0.1:{etcd_server}")))
            # etcd wins: premig visible, localonly NOT seeded
            assert iam2.get_credentials("premig") is not None
            assert iam2.get_credentials("localonly") is None
        finally:
            sets2.close()
    finally:
        sets.close()
