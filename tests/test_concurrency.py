"""Concurrency races: parallel PUT/GET/DELETE/heal on one erasure set
and through the live S3 server (the reference runs its whole suite under
-race and drives mint concurrently; Python's analog is real thread
interleaving over the same namespace + invariant checks)."""

from __future__ import annotations

import hashlib
import http.client
import os
import random
import threading
import urllib.parse

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("racetestkey1", "racetestsecret1")
REGION = "us-east-1"


@pytest.fixture()
def sets(tmp_path):
    s = ErasureSets.from_drives(
        [str(tmp_path / f"d{i}") for i in range(6)], 1, 6, 2,
        block_size=1 << 16)
    yield s
    s.close()


def _run_threads(fns, timeout=120):
    errs: list = []

    def wrap(fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — surfaced by the test
            errs.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), "deadlocked threads"
    return errs


def test_concurrent_puts_same_key_last_writer_wins(sets):
    """N writers hammer ONE key; afterwards the object must be exactly
    one writer's payload (never interleaved shards)."""
    sets.make_bucket("race")
    payloads = [bytes([i]) * 120_000 for i in range(8)]

    def put(i):
        def run():
            sets.put_object("race", "contended", payloads[i])
        return run

    errs = _run_threads([put(i) for i in range(8)])
    assert errs == []
    _, stream = sets.get_object("race", "contended")
    got = b"".join(stream)
    assert got in payloads, "interleaved write detected"


def test_concurrent_put_get_delete_mix(sets):
    """Readers/writers/deleters over a shared keyspace: every GET must
    return a complete consistent value or a clean ObjectNotFound."""
    sets.make_bucket("mix")
    keys = [f"k{i}" for i in range(6)]
    for k in keys:
        sets.put_object("mix", k, hashlib.sha256(k.encode()).digest()
                        * 2000)
    stop = threading.Event()
    bad: list = []

    def writer():
        rng = random.Random(1)
        while not stop.is_set():
            k = rng.choice(keys)
            sets.put_object("mix", k,
                            hashlib.sha256(k.encode()).digest() * 2000)

    def reader():
        rng = random.Random(2)
        while not stop.is_set():
            k = rng.choice(keys)
            try:
                _, stream = sets.get_object("mix", k)
                got = b"".join(stream)
            except (api_errors.ObjectNotFound,
                    api_errors.InsufficientReadQuorum):
                continue
            want = hashlib.sha256(k.encode()).digest() * 2000
            if got != want:
                bad.append((k, len(got)))

    def deleter():
        rng = random.Random(3)
        while not stop.is_set():
            k = rng.choice(keys)
            try:
                sets.delete_object("mix", k)
            except api_errors.ObjectApiError:
                pass
            sets.put_object("mix", k,
                            hashlib.sha256(k.encode()).digest() * 2000)

    threads = [threading.Thread(target=f)
               for f in (writer, writer, reader, reader, reader, deleter)]
    for t in threads:
        t.start()
    import time
    time.sleep(4.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not bad, f"torn reads: {bad[:3]}"


def test_concurrent_heal_and_reads(sets, tmp_path):
    """Healing a degraded object while readers stream it."""
    import shutil
    sets.make_bucket("hb")
    payload = os.urandom(300_000)
    sets.put_object("hb", "obj", payload)
    eng = sets.sets[0]
    # wipe one drive's shard files for the object (leave format intact)
    victim = eng.disks[2]
    shutil.rmtree(os.path.join(victim.root, "hb"), ignore_errors=True)

    def read():
        for _ in range(5):
            _, stream = sets.get_object("hb", "obj")
            assert b"".join(stream) == payload

    def heal():
        try:
            eng.heal_bucket("hb")
            eng.heal_object("hb", "obj")
        except api_errors.ObjectApiError:
            pass

    errs = _run_threads([read, read, heal, heal])
    assert errs == []
    _, stream = sets.get_object("hb", "obj")
    assert b"".join(stream) == payload


def test_concurrent_multipart_sessions(sets):
    """Parallel multipart uploads to distinct keys + the same key."""
    from minio_tpu.object.multipart import CompletePart
    sets.make_bucket("mpb")

    def upload(key, seed):
        def run():
            uid = sets.new_multipart_upload("mpb", key)
            rng = random.Random(seed)
            p = bytes([rng.randrange(256)]) * (5 << 20)
            info = sets.put_object_part("mpb", key, uid, 1, p)
            sets.complete_multipart_upload(
                "mpb", key, uid, [CompletePart(1, info.etag)])
        return run

    errs = _run_threads([upload("a", 1), upload("b", 2), upload("c", 3),
                         upload("same", 4), upload("same", 5)])
    assert errs == []
    for k in ("a", "b", "c", "same"):
        info = sets.get_object_info("mpb", k)
        assert info.size == 5 << 20


def test_concurrent_s3_requests(tmp_path):
    """Thread pool hammering the live server across the API surface."""
    drives = [str(tmp_path / f"d{i}") for i in range(4)]
    s = ErasureSets.from_drives(drives, 1, 4, 2, block_size=1 << 16)
    srv = S3Server(s, creds=CREDS, region=REGION).start()
    try:
        def req(method, path, body=b"", query=None):
            query = {k: [v] for k, v in (query or {}).items()}
            qs = urllib.parse.urlencode(
                {k: v[0] for k, v in query.items()})
            hdrs = {"host": f"127.0.0.1:{srv.port}"}
            hdrs = sig.sign_v4(method, path, query, hdrs,
                               hashlib.sha256(body).hexdigest(), CREDS,
                               REGION)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=60)
            conn.request(method, path + (f"?{qs}" if qs else ""),
                         body=body, headers=hdrs)
            r = conn.getresponse()
            data = r.read()
            conn.close()
            return r.status, data

        assert req("PUT", "/raceb")[0] == 200

        def worker(i):
            def run():
                body = bytes([i]) * 50_000
                assert req("PUT", f"/raceb/o{i}", body=body)[0] == 200
                st, got = req("GET", f"/raceb/o{i}")
                assert st == 200 and got == body
                st, listing = req("GET", "/raceb",
                                  query={"list-type": "2"})
                assert st == 200
                assert req("DELETE", f"/raceb/o{i}")[0] == 204
            return run

        errs = _run_threads([worker(i) for i in range(12)])
        assert errs == []
    finally:
        srv.stop()
        s.close()