"""Partition-tolerance tests: seeded NaughtyNet chaos through the
internode transport, peer membership generation fencing, split-brain-
safe registries, and dsync lease fencing.

Invariants (the acceptance bar of the partition-tolerance PR):
  * a partitioned link fails like an unreachable host on BOTH the
    outbound dial and the inbound verb — bounded by deadlines, never a
    parked reader;
  * fan-outs degrade to the reachable peers and heal back to the full
    merge once the partition clears;
  * a restarted/replaced peer's new incarnation never inherits its
    predecessor's per-peer state (generation fencing);
  * same-epoch/different-lineage registry copies are a detected fork —
    surfaced by fsck with an archiving repair, never silently merged —
    and minority-side registry commits are refused by write quorum;
  * a lock holder partitioned past its lease comes back FENCED.

Every schedule-driven test prints its seed; a failing run reproduces
exactly via MINIO_TPU_CHAOS_SEED=<seed>. The in-process tests run in
tier-1; the real-subprocess 2-node matrix is marked slow.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from minio_tpu.distributed import membership
from minio_tpu.distributed.dsync import DRWMutex
from minio_tpu.distributed.local_locker import LocalLocker
from minio_tpu.distributed.naughtynet import (NET, NetSchedule,
                                              handle_admin)
from minio_tpu.distributed.peer_rpc import (NotificationSys,
                                            PeerRPCClient, PeerRPCServer)
from minio_tpu.distributed.transport import (NetworkError, RPCHandler,
                                             RPCServer, RestClient)
from minio_tpu.object.fsck import run_fsck
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.replicate.targets import (TARGETS_OBJECT, ReplTargetError,
                                         SiteTarget, TargetRegistry,
                                         new_arn)
from minio_tpu.storage.xl_storage import MINIO_META_BUCKET
from minio_tpu.utils import healthtrack, regfence

pytestmark = pytest.mark.chaos

AK, SK = "peerak", "peersecret12345"
K, M, NDISKS = 4, 2, 6
BLOCK = 1 << 16


def chaos_seed(default: int) -> int:
    return int(os.environ.get("MINIO_TPU_CHAOS_SEED", "0") or 0) or default


def announce(seed: int) -> None:
    # pytest shows captured stdout on failure: the seed reproduces the
    # exact fault schedule (MINIO_TPU_CHAOS_SEED=<seed>)
    print(f"fault-schedule seed={seed} "
          f"(MINIO_TPU_CHAOS_SEED={seed} reproduces)")


@pytest.fixture(autouse=True)
def _clean_slate():
    """Every test starts and ends with the process-global fault
    controller disarmed and a fresh membership incarnation."""
    NET.reset()
    membership.TRACKER.reset()
    membership.set_local_node("")
    yield
    NET.reset()
    membership.TRACKER.reset()
    membership.set_local_node("")


def wait_until(pred, timeout: float = 10.0, interval: float = 0.1,
               what: str = "condition") -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"{what} not reached within {timeout}s")


# ---------------------------------------------------------------------------
# schedule determinism
# ---------------------------------------------------------------------------

def test_schedule_replay_is_deterministic():
    seed = chaos_seed(4242)
    announce(seed)
    mk = lambda s: NetSchedule(seed=s, delay_rate=0.4, delay_s=0.002,
                               jitter_s=0.003, reset_rate=0.4)
    a, b = mk(seed), mk(seed)
    seq_a = [(a.delay_for("verb", n), a.resets("verb", n))
             for n in range(128)]
    seq_b = [(b.delay_for("verb", n), b.resets("verb", n))
             for n in range(128)]
    assert seq_a == seq_b, "same seed must replay the same faults"
    c = mk(seed + 1)
    seq_c = [(c.delay_for("verb", n), c.resets("verb", n))
             for n in range(128)]
    assert seq_c != seq_a, "a different seed must diverge"
    # the schedule actually fires — and not on every call
    assert any(d > 0 for d, _ in seq_a)
    assert any(r for _, r in seq_a)
    assert any(d == 0 and not r for d, r in seq_a)


def test_schedule_verb_filter_and_jitter_bounds():
    s = NetSchedule(seed=7, delay_rate=1.0, delay_s=0.01, jitter_s=0.02,
                    reset_rate=1.0, fault_verbs=("hot",))
    assert s.delay_for("cold", 0) == 0.0
    assert not s.resets("cold", 0)
    for n in range(32):
        d = s.delay_for("hot", n)
        assert 0.01 <= d < 0.03 + 1e-9
        assert s.resets("hot", n)


def test_partition_window_opens_and_expires():
    NET.partition("x", "y", duration_s=0.3)
    assert NET.blocked("x", "y") and NET.blocked("y", "x")
    wait_until(lambda: not NET.blocked("x", "y"), timeout=2.0,
               interval=0.05, what="timed partition auto-heal")
    # delayed-open window: inactive now, active after after_s
    NET.partition("p", "q", after_s=0.25)
    assert not NET.blocked("p", "q")
    wait_until(lambda: NET.blocked("p", "q"), timeout=2.0,
               interval=0.05, what="delayed partition window open")
    NET.heal("p", "q")
    assert not NET.blocked("p", "q")


def test_admin_ops_roundtrip_in_process():
    st = handle_admin({"op": "partition", "src": "a", "dst": "b",
                       "oneway": True})
    assert st["enabled"]
    assert [(r["src"], r["dst"]) for r in st["rules"]] == [("a", "b")]
    st = handle_admin({"op": "configure", "seed": 99,
                       "delay_rate": 0.5, "delay_s": 0.001})
    assert st["schedule"]["seed"] == 99
    st = handle_admin({"op": "heal"})
    assert st["rules"] == []
    st = handle_admin({"op": "reset"})
    assert not st["enabled"] and st["schedule"] is None
    with pytest.raises(ValueError):
        handle_admin({"op": "no-such-op"})


# ---------------------------------------------------------------------------
# transport under partition (in-process peer mesh)
# ---------------------------------------------------------------------------

@pytest.fixture()
def duo():
    """Two peer nodes whose node ids are their real wire addresses,
    plus one observer client per node (all node_id='observer')."""
    hosts, servers, clients = [], [], []
    for i in range(2):
        host = RPCServer().start()
        nid = f"127.0.0.1:{host.port}"
        srv = PeerRPCServer(AK, SK, node_id=nid)
        srv.get_server_info = lambda i=i: {"idx": i}
        srv.get_metrics_text = \
            lambda i=i: f"# HELP probe node{i}\nprobe{{n=\"{i}\"}} 1\n"
        host.mount(srv.handler)
        hosts.append(host)
        servers.append(srv)
        clients.append(PeerRPCClient("127.0.0.1", host.port, AK, SK,
                                     timeout=3.0, node_id="observer"))
    yield hosts, servers, clients
    for c in clients:
        c.close()
    for h in hosts:
        h.stop()


def test_partition_blocks_dial_then_heals(duo):
    hosts, servers, clients = duo
    assert clients[0].server_info()["idx"] == 0
    NET.partition("observer", servers[0].node_id)
    # the cut link fails like an unreachable host: no result, client
    # transport flips offline, drop counted
    assert clients[0].server_info() is None
    assert not clients[0].rc.online
    assert NET.stats["blocked"] >= 1
    # the OTHER link is untouched
    assert clients[1].server_info()["idx"] == 1
    # while offline, fan-out verbs shed without dialing (no new blocks)
    blocked_before = NET.stats["blocked"]
    assert clients[0].server_info() is None
    assert NET.stats["blocked"] == blocked_before
    # heal: the background probe re-admits the host and calls succeed
    NET.heal()
    wait_until(lambda: clients[0].rc.online, timeout=15.0,
               what="post-heal probe re-admission")
    assert clients[0].server_info()["idx"] == 0


def test_oneway_partition_is_asymmetric(duo):
    hosts, servers, _clients = duo
    a_id, b_id = servers[0].node_id, servers[1].node_id
    # a client speaking AS node a, dialing node b — and the reverse
    a_to_b = PeerRPCClient("127.0.0.1", hosts[1].port, AK, SK,
                           timeout=3.0, node_id=a_id)
    b_to_a = PeerRPCClient("127.0.0.1", hosts[0].port, AK, SK,
                           timeout=3.0, node_id=b_id)
    try:
        NET.partition(a_id, b_id, oneway=True)
        assert a_to_b.server_info() is None, "a->b is cut"
        info = b_to_a.server_info()
        assert info and info["idx"] == 0, "b->a still works"
    finally:
        a_to_b.close()
        b_to_a.close()


def test_inbound_drop_maps_to_unreachable_host():
    """A rule the SERVING side enforces (its node id is not the dial
    address) refuses the verb pre-dispatch; the caller sees the same
    conn_failure an unreachable host raises — one side's injector is
    enough to cut a link."""
    host = RPCServer().start()
    srv = PeerRPCServer(AK, SK, node_id="srv-one")
    host.mount(srv.handler)
    rc = RestClient("127.0.0.1", host.port, "/minio/peer/v1", AK, SK,
                    timeout=3.0)
    rc.node_id = "caller"
    try:
        assert rc.call_json("server-info") is not None
        NET.partition("caller", "srv-one", oneway=True)
        with pytest.raises(NetworkError) as ei:
            rc.call_json("server-info")
        assert ei.value.conn_failure
        assert not rc.online
    finally:
        rc.close()
        host.stop()


def test_metrics_scrape_degrades_then_heals(duo):
    """Federated-scrape satellite: under an asymmetric partition the
    cluster scrape returns within its deadline with the cut peer
    marked failed; after heal the full merge is back."""
    hosts, servers, clients = duo
    ns = NotificationSys(clients)
    before = dict(ns.metrics_text_all(deadline=2.0))
    assert all(v is not None for v in before.values())
    NET.partition("observer", servers[0].node_id, oneway=True)
    t0 = time.monotonic()
    during = ns.metrics_text_all(deadline=2.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 8.0, f"degraded scrape must stay bounded ({elapsed:.1f}s)"
    per_peer = dict(during)
    assert per_peer[clients[0].addr] is None, "cut peer scrape-failed"
    assert "node1" in per_peer[clients[1].addr], "reachable peer served"
    NET.heal()
    wait_until(lambda: clients[0].rc.online, timeout=15.0,
               what="post-heal probe re-admission")
    healed = dict(ns.metrics_text_all(deadline=2.0))
    assert all(v is not None for v in healed.values()), \
        "healed partition must restore the full merge"


def test_streamed_read_deadline_fires_on_midstream_partition(monkeypatch):
    """Partition-after-headers: the server stream goes silent, the
    per-read socket deadline turns the parked read into a bounded
    NetworkError(conn_failure) instead of a forever-hang."""
    monkeypatch.setenv("MINIO_TPU_RPC_STREAM_READ_S", "1.0")
    h = RPCHandler("/drip/v1", AK, SK, node_id="streamer")

    def drip(_args, _body):
        def gen():
            for _ in range(200):
                yield b"tick\n"
                time.sleep(0.05)
        return gen()

    h.register("drip", drip)
    host = RPCServer().start()
    host.mount(h)
    rc = RestClient("127.0.0.1", host.port, "/drip/v1", AK, SK,
                    timeout=30.0)
    rc.node_id = "watcher"
    # armed BEFORE the stream opens so the wrapper is installed; the
    # window opens mid-stream (the classic partition-after-headers)
    NET.partition("watcher", "streamer", oneway=True, after_s=0.4)
    try:
        resp = rc.call("drip", stream_response=True)
        assert resp.readline() == b"tick\n", "pre-window reads flow"
        t0 = time.monotonic()
        with pytest.raises(NetworkError) as ei:
            while True:
                line = resp.readline()
                if not line:
                    raise AssertionError("stream ended cleanly under "
                                         "partition")
        elapsed = time.monotonic() - t0
        assert ei.value.conn_failure
        assert "read deadline" in str(ei.value)
        assert elapsed < 6.0, \
            f"reader must fail by deadline, not TCP timeout ({elapsed:.1f}s)"
        assert NET.stats["stream_stalls"] >= 1
        resp.close()
    finally:
        rc.close()
        host.stop()


# ---------------------------------------------------------------------------
# membership: generation fencing
# ---------------------------------------------------------------------------

def test_generation_change_fires_fencing_listeners():
    peer = "10.9.9.9:9000"
    events: list = []
    membership.TRACKER.add_listener(
        lambda p, o, n: events.append((p, o, n)))
    # stale per-peer evidence accumulated against the OLD incarnation
    healthtrack.observe_peer(peer, "read", 0.5)
    assert healthtrack.TRACKER.percentile("peer", peer, 0.99) is not None
    assert membership.TRACKER.observe(peer, 100, "nodeX") is False, \
        "first sighting is not a change"
    assert membership.TRACKER.observe(peer, 100) is False
    assert events == []
    assert membership.TRACKER.observe(peer, 101) is True
    assert events == [(peer, 100, 101)]
    assert membership.TRACKER.generation_of(peer) == 101
    # the transport's import-time listener cleared the latency window
    assert healthtrack.TRACKER.percentile("peer", peer, 0.99) is None
    # garbage observations are ignored
    assert membership.TRACKER.observe("", 5) is False
    assert membership.TRACKER.observe(peer, 0) is False


def test_generation_rides_the_wire_both_ways():
    """The response headers feed the caller's tracker; a re-minted
    server generation (a restart) is positively detected on the next
    exchange."""
    host = RPCServer().start()
    srv = PeerRPCServer(AK, SK, node_id="gen-srv")
    host.mount(srv.handler)
    c = PeerRPCClient("127.0.0.1", host.port, AK, SK, timeout=3.0,
                      node_id="gen-cli")
    addr = c.addr
    events: list = []
    membership.TRACKER.add_listener(
        lambda p, o, n: events.append((p, o, n)))
    try:
        assert c.server_info() is not None
        gen1 = membership.TRACKER.generation_of(addr)
        assert gen1 == membership.local_generation()
        # the serving side ALSO observed the caller's identity headers
        assert membership.TRACKER.generation_of("gen-cli") == gen1
        # simulate the server restarting: a freshly minted generation
        membership.TRACKER.local_generation = gen1 + 1
        assert c.server_info() is not None
        assert membership.TRACKER.generation_of(addr) == gen1 + 1
        assert (addr, gen1, gen1 + 1) in events
    finally:
        c.close()
        host.stop()


# ---------------------------------------------------------------------------
# split-brain-safe registries: write quorum + fork detection
# ---------------------------------------------------------------------------

class _StubPool:
    """Minimal pool: the two object verbs the registry persistence
    path touches, plus a reachability switch standing in for a
    partition."""

    def __init__(self):
        self.objs: dict = {}
        self.reachable = True

    def put_object(self, _bucket, key, data, **_kw):
        if not self.reachable:
            raise OSError("stub pool partitioned away")
        self.objs[key] = bytes(data)

    def get_object(self, _bucket, key):
        if not self.reachable:
            raise OSError("stub pool partitioned away")
        if key not in self.objs:
            from minio_tpu.object import api_errors
            raise api_errors.ObjectApiError(f"no such key {key}")
        return None, iter([self.objs[key]])


class _StubLayer:
    def __init__(self, pools):
        self.server_sets = pools


def test_registry_write_quorum_refuses_minority_commit(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_REGISTRY_WRITE_QUORUM", "majority")
    pools = [_StubPool(), _StubPool(), _StubPool()]
    reg = TargetRegistry(object_layer=_StubLayer(pools), site_id="site")
    target = SiteTarget(arn=new_arn("dst"), bucket="b",
                        dest_bucket="dst", type="layer")
    # majority of pools partitioned away: the epoch bump must refuse
    pools[1].reachable = pools[2].reachable = False
    with pytest.raises(ReplTargetError, match="need 2"):
        reg.add(target, client=object())
    assert target.arn not in reg.targets, "refused add rolled back"
    assert TARGETS_OBJECT not in pools[1].objs
    # heal one pool: majority reachable again, the commit lands
    pools[1].reachable = True
    epoch = reg.add(target, client=object())
    assert epoch >= 1
    for p in (pools[0], pools[1]):
        doc = json.loads(p.objs[TARGETS_OBJECT].decode())
        # the commit is lineage-stamped and the chain verifies
        assert doc["lineage"] == regfence.lineage(
            doc["parent_lineage"], doc["epoch"], doc["writer"])


def test_registry_write_quorum_default_keeps_legacy_behavior(monkeypatch):
    monkeypatch.delenv("MINIO_TPU_REGISTRY_WRITE_QUORUM", raising=False)
    pools = [_StubPool(), _StubPool(), _StubPool()]
    pools[1].reachable = pools[2].reachable = False
    reg = TargetRegistry(object_layer=_StubLayer(pools), site_id="site")
    target = SiteTarget(arn=new_arn("dst"), bucket="b",
                        dest_bucket="dst", type="layer")
    # default quorum "1": one pool is enough (at-least-one legacy rule)
    assert reg.add(target, client=object()) >= 1
    assert TARGETS_OBJECT in pools[0].objs


def _fork_doc(epoch: int, writer: str) -> dict:
    return {"epoch": epoch, "updated": time.time(), "site_id": "s",
            "targets": [], "writer": writer, "parent_lineage": "",
            "lineage": regfence.lineage("", epoch, writer)}


def make_zones(tmp_path, pools=2, tag="p"):
    zz = ErasureServerSets(
        [ErasureSets.from_drives(
            [str(tmp_path / f"{tag}{p}d{j}") for j in range(NDISKS)],
            1, NDISKS, M, block_size=BLOCK, enable_mrf=False)
         for p in range(pools)],
        load_topology=False)
    zz.make_bucket("b")
    return zz


def test_registry_fork_detected_and_repaired_never_merged(tmp_path):
    zz = make_zones(tmp_path, pools=2)
    try:
        doc_a, doc_b = _fork_doc(7, "nodeA"), _fork_doc(7, "nodeB")
        raw_a = json.dumps(doc_a).encode()
        raw_b = json.dumps(doc_b).encode()
        zz.server_sets[0].put_object(MINIO_META_BUCKET, TARGETS_OBJECT,
                                     raw_a)
        zz.server_sets[1].put_object(MINIO_META_BUCKET, TARGETS_OBJECT,
                                     raw_b)
        # load NEVER coin-flips: the deterministic winner is nodeB
        # (highest (epoch, writer, lineage)) regardless of pool order
        reg = TargetRegistry(object_layer=zz)
        assert reg.load()
        assert (reg.epoch, reg.writer) == (7, "nodeB")
        # the fork is a detected finding, not a silent merge
        rep = run_fsck(zz, tmp_age_s=0)
        forks = [f for f in rep.findings
                 if f.cls == "registry_epoch_fork"]
        assert len(forks) == 1
        assert forks[0].object == TARGETS_OBJECT
        assert "nodeB" in forks[0].detail
        # repair: loser archived (never deleted), every pool converges
        rep = run_fsck(zz, repair=True, tmp_age_s=0)
        assert rep.repaired_counts().get("registry_epoch_fork") == 1
        from minio_tpu.object.fsck import _get_pool_bytes
        for pool in zz.server_sets:
            assert _get_pool_bytes(pool, TARGETS_OBJECT) == raw_b
        archived = _get_pool_bytes(
            zz.server_sets[0],
            f"{TARGETS_OBJECT}.fork-{doc_a['lineage']}")
        assert archived == raw_a
        # a second audit is clean — archives are not re-audited
        rep = run_fsck(zz, tmp_age_s=0)
        assert not [f for f in rep.findings
                    if f.cls == "registry_epoch_fork"]
    finally:
        zz.close()


def test_fork_audit_ignores_legacy_and_agreeing_docs(tmp_path):
    zz = make_zones(tmp_path, pools=2)
    try:
        # same lineage on both pools: agreement, no finding
        doc = _fork_doc(3, "nodeA")
        raw = json.dumps(doc).encode()
        for pool in zz.server_sets:
            pool.put_object(MINIO_META_BUCKET, TARGETS_OBJECT, raw)
        rep = run_fsck(zz, tmp_age_s=0)
        assert not [f for f in rep.findings
                    if f.cls == "registry_epoch_fork"]
        # pre-fencing docs (no lineage) cannot be distinguished: the
        # audit must not flag legacy deployments
        legacy = {"epoch": 3, "targets": [], "site_id": "s"}
        zz.server_sets[0].put_object(MINIO_META_BUCKET, TARGETS_OBJECT,
                                     json.dumps(legacy).encode())
        rep = run_fsck(zz, tmp_age_s=0)
        assert not [f for f in rep.findings
                    if f.cls == "registry_epoch_fork"]
    finally:
        zz.close()


# ---------------------------------------------------------------------------
# dsync: lease expiry + returning-holder fencing
# ---------------------------------------------------------------------------

def test_partitioned_lock_holder_expires_and_returns_fenced():
    lockers = [LocalLocker() for _ in range(3)]
    a = DRWMutex(lockers, ["vol/obj"], owner="holderA")
    assert a.get_lock(timeout=2.0, source="test")
    assert a.check() is True, "held lease refreshes on a quorum"
    # concurrent acquire fails while the lease is live
    b = DRWMutex(lockers, ["vol/obj"], owner="holderB")
    assert not b.get_lock(timeout=0.5)
    # holder A partitions away: its refreshes stop arriving and the
    # grant ages past validity on every locker
    time.sleep(0.05)
    for lk in lockers:
        assert lk.expire_old_locks(validity=0.01) >= 1
    # the lease is re-grantable — the cluster makes progress
    assert b.get_lock(timeout=2.0, source="test")
    assert b.check() is True
    # ...and the returning holder is FENCED: its grant is gone, check()
    # fails closed and latches lock_lost before it can touch the
    # protected resource
    assert a.check() is False
    assert a.lock_lost is True
    assert a.check() is False, "lock_lost latches"
    b.unlock()
    a.unlock()


# ---------------------------------------------------------------------------
# real-subprocess smoke + 2-node partition matrix
# ---------------------------------------------------------------------------

NAUGHTY_ENV = {"MINIO_TPU_NAUGHTYNET": "on"}


@pytest.mark.slow
def test_naughtynet_admin_verb_gated_and_live(tmp_path):
    """Admin-verb smoke on one real process: the verb answers only
    with MINIO_TPU_NAUGHTYNET=on, rules install/heal, and SIGSTOP/
    SIGCONT pause survives."""
    from minio_tpu.madmin import AdminClientError
    from tests.harness.proc import ProcNode
    node = ProcNode(tmp_path, n_drives=4, name="nn")
    node.start(extra_env=NAUGHTY_ENV)
    try:
        st = node.naughtynet({"op": "status"})
        assert st["enabled"] is False and st["rules"] == []
        assert st["local_node"] == node.addr
        st = node.naughtynet({"op": "partition", "src": node.addr,
                              "dst": "10.0.0.2:9000"})
        assert st["enabled"] and len(st["rules"]) == 2
        st = node.naughtynet({"op": "heal"})
        assert st["rules"] == []
        node.pause()
        time.sleep(0.3)
        node.resume()
        assert node.naughtynet({"op": "reset"})["enabled"] is False
    finally:
        node.close()
    # without the knob the verb refuses (it is a test-only surface)
    plain = ProcNode(tmp_path, n_drives=4, name="nn2")
    plain.start()
    try:
        with pytest.raises(AdminClientError):
            plain.naughtynet({"op": "status"})
    finally:
        plain.close()


@pytest.mark.slow
def test_two_node_partition_matrix(tmp_path):
    """The acceptance matrix on a REAL 2-process cluster (8-drive set
    split 4/4, parity 4): reads of acknowledged objects keep serving
    from the local quorum under a full partition, quorum writes are
    refused (never half-acked), heal converges both nodes to identical
    listings with zero acked-write loss, and fsck ends clean."""
    from minio_tpu.utils.s3client import S3ClientError
    from tests.harness.proc import heal, make_cluster, partition
    seed = chaos_seed(1717)
    announce(seed)
    nodes = make_cluster(tmp_path, n_nodes=2, n_drives=4, parity=4,
                         set_drive_count=8)
    boot_errs: list = []

    def boot(n):
        try:
            n.start(extra_env=NAUGHTY_ENV, timeout=120.0)
        except Exception as e:  # noqa: BLE001 — surfaced below
            boot_errs.append((n.name, e))

    threads = [threading.Thread(target=boot, args=(n,)) for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180.0)
    assert not boot_errs, f"cluster boot failed: {boot_errs}"
    try:
        n0, n1 = nodes
        n0.s3().make_bucket("pbkt")
        expect: dict[str, bytes] = {}
        for i in range(5):
            body = os.urandom(1 << 15) + bytes([i])
            n0.put("pbkt", f"pre/k{i}", body)
            expect[f"pre/k{i}"] = body

        partition(n0, n1)
        # bounded degradation: every acknowledged object still reads
        # from the local quorum (4 data shards live on n0's drives),
        # within deadlines — not TCP-timeout territory
        t0 = time.monotonic()
        for key, body in expect.items():
            assert n0.get("pbkt", key) == body
        elapsed = time.monotonic() - t0
        assert elapsed < 120.0, \
            f"partitioned reads must stay bounded ({elapsed:.1f}s)"
        # a quorum write (needs 5 of 8 drives) must refuse — an ack
        # during the partition would be a durability lie. If it DID
        # ack, it joins the zero-loss ledger below.
        try:
            body = os.urandom(1 << 14)
            n0.put("pbkt", "during/k", body)
            expect["during/k"] = body
        except (S3ClientError, OSError):
            pass
        else:
            raise AssertionError(
                "minority-side write was acked under partition")

        heal(n0, n1)
        # convergence: post-heal writes succeed again (transport
        # probes re-admit the peer within seconds)
        deadline = time.monotonic() + 60.0
        body = os.urandom(1 << 14)
        while True:
            try:
                n0.put("pbkt", "post/k", body)
                expect["post/k"] = body
                break
            except (S3ClientError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(1.0)
        # zero acked-write loss, from BOTH nodes, byte-identical
        for key, want in expect.items():
            assert n0.get("pbkt", key) == want
            assert n1.get("pbkt", key) == want
        assert n0.listing("pbkt") == n1.listing("pbkt"), \
            "healed nodes must converge to identical listings"
        # the tree audits clean after repair (MRF may still be
        # draining shards the partition starved — poll briefly)
        n0.fsck(repair=True)
        deadline = time.monotonic() + 60.0
        while True:
            rep = n0.fsck(repair=True)
            bad = [f for f in rep.get("findings", [])
                   if not f.get("repaired")]
            if not bad:
                break
            if time.monotonic() > deadline:
                raise AssertionError(f"fsck never converged: {bad}")
            time.sleep(2.0)
        assert not [f for f in rep.get("findings", [])
                    if f.get("class") == "registry_epoch_fork"], \
            "a partition alone must never manufacture a registry fork"
    finally:
        for n in nodes:
            n.close()
