"""CI pin for the notification A/B smoke: `bench.py
--ab-notify-smoke` must keep producing its shape (baseline +
during-notify percentiles, a fully drained plane with zero loss, the
delivery-lag histogram) in seconds — the gate beside tier1_diff that
keeps the bench runnable."""


def test_ab_notify_smoke_shape():
    import bench
    ab = bench.bench_notify_ab(streams=2, size=1 << 18, drives=6,
                               webhook_delay_s=0.01, block=1 << 16)
    assert set(ab) >= {"config", "baseline", "during_notify",
                       "plane_final", "webhook_received",
                       "put_p99_degradation_x", "lag_histogram"}
    for phase in ("baseline", "during_notify"):
        assert ab[phase]["p50_ms"] > 0 and ab[phase]["p99_ms"] > 0
    # zero loss: the measured PUT rounds (2 streams x 2 rounds) all
    # reached the webhook once the drain finished
    assert ab["webhook_received"] >= 4
    assert ab["plane_final"]["pending"] == 0
    assert ab["plane_final"]["backlog"] == 0
    assert ab["plane_final"]["dropped"] == 0
    assert ab["put_p99_degradation_x"] > 0
    assert ab["lag_histogram"].get("count", 0) >= 4
