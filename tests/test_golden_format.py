"""Golden-bytes pins for the on-disk wire formats.

The xl.meta msgpack shape and format.json JSON shape are CONTRACTS with
the reference implementation (cmd/xl-storage-format-v2.go:34-98,
cmd/format-erasure.go:106-127): field names, integer widths, bin-vs-str
types, and the header must not drift. These fixtures freeze the exact
bytes our serializers emit for fixed inputs — any refactor that changes
the wire image fails here and must consciously update the pin.

Also: streaming merge-walk listing behavior at scale (no full
materialization, correct pagination)."""

from __future__ import annotations

import json

import msgpack
import pytest

from minio_tpu.storage.datatypes import (ChecksumInfo, ErasureInfo,
                                         FileInfo, ObjectPartInfo)
from minio_tpu.storage.format import FormatErasureV3
from minio_tpu.storage.xl_meta import XLMetaV2

GOLDEN_XLMETA_OBJECT = (
    "584c32203120202081a856657273696f6e739182a45479706501a556324f626ade00"
    "11a24944c41011111111222233334444555555555555a444446972c410aaaaaaaabb"
    "bbccccddddeeeeeeeeeeeea64563416c676f01a345634d04a345634e02a745634253"
    "697a65ce00100000a74563496e64657803a6456344697374c406030405060102a843"
    "53756d416c676f01a8506172744e756d739101a950617274455461677391d9206434"
    "316438636439386630306232303465393830303939386563663834323765a9506172"
    "7453697a657391ce00100000aa506172744153697a657391ce00100000a453697a65"
    "ce00100000a54d54696d65cf17979cfe362a0000a74d65746153797381bc782d6d69"
    "6e696f2d696e7465726e616c2d636f6d7072657373696f6ec4047a737464a74d6574"
    "6155737282a465746167d92064343164386364393866303062323034653938303039"
    "39386563663834323765ac636f6e74656e742d74797065aa746578742f706c61696e"
)

GOLDEN_DELETE_SUFFIX = (
    "82a45479706502a644656c4f626a82a24944c410999999998888777766665555555"
    "55555a54d54696d65cf17979cfe71c4ca00"
)


def _object_fi() -> FileInfo:
    return FileInfo(
        volume="b", name="o",
        version_id="11111111-2222-3333-4444-555555555555",
        data_dir="aaaaaaaa-bbbb-cccc-dddd-eeeeeeeeeeee",
        mod_time=1700000000.0, size=1048576,
        metadata={"etag": "d41d8cd98f00b204e9800998ecf8427e",
                  "content-type": "text/plain",
                  "x-minio-internal-compression": "zstd"},
        parts=[ObjectPartInfo(
            number=1, etag="d41d8cd98f00b204e9800998ecf8427e",
            size=1048576, actual_size=1048576)],
        erasure=ErasureInfo(
            algorithm="rs-vandermonde", data_blocks=4, parity_blocks=2,
            block_size=1048576, index=3, distribution=[3, 4, 5, 6, 1, 2],
            checksums=[ChecksumInfo(1, "highwayhash256S", b"")]))


def test_xlmeta_golden_bytes_object():
    z = XLMetaV2()
    z.add_version(_object_fi())
    assert z.dumps().hex() == GOLDEN_XLMETA_OBJECT


def test_xlmeta_golden_bytes_delete_marker():
    z = XLMetaV2()
    z.add_version(_object_fi())
    z.add_version(FileInfo(
        volume="b", name="o",
        version_id="99999999-8888-7777-6666-555555555555",
        deleted=True, mod_time=1700000001.0))
    blob = z.dumps().hex()
    assert blob.endswith(GOLDEN_DELETE_SUFFIX)
    # two journal entries
    assert XLMetaV2.loads(bytes.fromhex(blob)).versions.__len__() == 2


def test_xlmeta_wire_shapes():
    """Pin the msgp-level invariants the reference binary depends on:
    header, field names, bin-typed UUIDs, nanosecond int64 mtimes."""
    z = XLMetaV2()
    z.add_version(_object_fi())
    blob = z.dumps()
    assert blob[:4] == b"XL2 " and blob[4:8] == b"1   "
    doc = msgpack.unpackb(blob[8:], raw=False)
    (entry,) = doc["Versions"]
    assert entry["Type"] == 1
    obj = entry["V2Obj"]
    assert sorted(obj) == sorted([
        "ID", "DDir", "EcAlgo", "EcM", "EcN", "EcBSize", "EcIndex",
        "EcDist", "CSumAlgo", "PartNums", "PartETags", "PartSizes",
        "PartASizes", "Size", "MTime", "MetaSys", "MetaUsr"])
    assert isinstance(obj["ID"], bytes) and len(obj["ID"]) == 16
    assert isinstance(obj["DDir"], bytes) and len(obj["DDir"]) == 16
    assert isinstance(obj["EcDist"], bytes)
    assert obj["MTime"] == 1700000000 * 10**9
    assert obj["EcM"] == 4 and obj["EcN"] == 2


def test_format_json_golden():
    fmt = FormatErasureV3(
        id="0a2bd4e3-2cd8-4b5e-8dd5-0f1b4bcd63bb",
        this="11111111-2222-3333-4444-555555555555",
        sets=[["11111111-2222-3333-4444-555555555555",
               "66666666-7777-8888-9999-aaaaaaaaaaaa"]])
    got = json.loads(fmt.to_json())
    assert got == {
        "version": "1",
        "format": "xl",
        "id": "0a2bd4e3-2cd8-4b5e-8dd5-0f1b4bcd63bb",
        "xl": {
            "version": "3",
            "this": "11111111-2222-3333-4444-555555555555",
            "sets": [["11111111-2222-3333-4444-555555555555",
                      "66666666-7777-8888-9999-aaaaaaaaaaaa"]],
            "distributionAlgo": "SIPMOD",
        },
    }
    rt = FormatErasureV3.from_json(fmt.to_json())
    assert rt.this == fmt.this and rt.sets == fmt.sets


# ---------------------------------------------------------------------------
# streaming merge-walk listing
# ---------------------------------------------------------------------------

def test_merged_names_is_lazy_and_paginates(tmp_path):
    from minio_tpu.object.sets import ErasureSets
    drives = [str(tmp_path / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    eng = sets.sets[0]
    sets.make_bucket("lots")
    for i in range(30):
        sets.put_object("lots", f"k{i:04d}", b"v")
        sets.put_object("lots", f"other/{i:04d}", b"v")

    # generator: consuming one page never walks the whole namespace
    gen = eng._merged_names("lots", "k")
    first = next(gen)
    assert first == "k0000"

    # prefix narrowing + marker pagination through list_objects
    objs, _, trunc = eng.list_objects("lots", prefix="k", max_keys=10)
    assert [o.name for o in objs] == [f"k{i:04d}" for i in range(10)]
    assert trunc
    objs2, _, _ = eng.list_objects("lots", prefix="k",
                                   marker=objs[-1].name, max_keys=10)
    assert [o.name for o in objs2] == [f"k{i:04d}" for i in range(10, 20)]

    # deep-prefix listing only returns the subtree
    objs3, _, _ = eng.list_objects("lots", prefix="other/000",
                                   max_keys=100)
    assert [o.name for o in objs3] == [f"other/{i:04d}" for i in range(10)]
    sets.close()

def test_xl_v1_json_migration(tmp_path):
    """A legacy xl.json drive entry is readable and migrates to xl.meta
    on first access (reference xl-storage-format-v1 migration)."""
    import json
    import os
    from minio_tpu.storage.xl_storage import XLStorage

    d = XLStorage(str(tmp_path / "legacy"))
    d.make_vol_bulk(".minio.sys", "b")
    obj_dir = tmp_path / "legacy" / "b" / "old-obj"
    os.makedirs(obj_dir)
    v1 = {
        "version": "1.0.1", "format": "xl",
        "stat": {"size": 1234, "modTime": "2020-09-01T12:00:00Z"},
        "erasure": {"algorithm": "klauspost/reedsolomon/vandermonde",
                    "data": 4, "parity": 2, "blockSize": 1048576,
                    "index": 3, "distribution": [3, 4, 5, 6, 1, 2],
                    "checksum": [{"name": "part.1",
                                  "algorithm": "highwayhash256S",
                                  "hash": ""}]},
        "minio": {"release": "RELEASE.2020"},
        "meta": {"etag": "abcd", "content-type": "text/plain"},
        "parts": [{"number": 1, "name": "part.1", "etag": "abcd",
                   "size": 1234, "actualSize": 1234}],
    }
    (obj_dir / "xl.json").write_text(json.dumps(v1))

    fi = d.read_version("b", "old-obj")
    assert fi.size == 1234
    assert fi.metadata["etag"] == "abcd"
    assert fi.erasure.data_blocks == 4 and fi.erasure.parity_blocks == 2
    assert fi.erasure.distribution == [3, 4, 5, 6, 1, 2]
    assert fi.mod_time > 0

    # migrated: xl.meta exists, xl.json is gone, re-read works
    assert (obj_dir / "xl.meta").exists()
    assert not (obj_dir / "xl.json").exists()
    fi2 = d.read_version("b", "old-obj")
    assert fi2.size == 1234

    # legacy entries are visible to the walk (listing path)
    names = [f.name for f in d.walk("b")]
    assert "old-obj" in names
