"""Pipelined data path: stage executor semantics, staging-pool
back-pressure, pipelined PUT correctness (short last block, zero-byte,
single-block, multi-batch), on-disk byte identity vs the serial loop,
GET lookahead prefetch, quorum-error propagation, and the OBD fault
counters."""

from __future__ import annotations

import io
import os

import numpy as np
import pytest

from minio_tpu.object import ErasureSetObjects, api_errors
from minio_tpu.object import engine as engine_mod
from minio_tpu.parallel import pipeline as pl
from minio_tpu.storage import XLStorage, errors as serr, new_format_erasure_v3
from minio_tpu.storage.naughty import NaughtyDisk

K, M = 4, 2
NDISKS = K + M
BLOCK = 1 << 16


def make_engine(tmp_path, sub="", naughty=False):
    fmts = new_format_erasure_v3(1, NDISKS)
    disks = []
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"{sub}d{j}"))
        d.write_format(fmts[0][j])
        disks.append(NaughtyDisk(d) if naughty else d)
    e = ErasureSetObjects(disks, K, M, block_size=BLOCK)
    e.make_bucket("b")
    return e


def payload(size, seed=7) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def put_pipelined(eng, name, data: bytes):
    """PUT through the pipelined loop regardless of size: an unknown
    stream length bypasses the fits-one-batch serial heuristic."""
    return eng.put_object("b", name, io.BytesIO(data), size=-1)


# ---------------------------------------------------------------------------
# StagePipeline executor
# ---------------------------------------------------------------------------

def test_stage_pipeline_preserves_order():
    seen: list[int] = []
    pipe = pl.StagePipeline([lambda x: x * 10,
                             lambda x: seen.append(x)], depth=2)
    for i in range(50):
        pipe.submit(i)
    pipe.close()
    assert seen == [i * 10 for i in range(50)]


def test_stage_pipeline_raises_original_error_and_drops():
    class Boom(RuntimeError):
        pass

    dropped: list = []

    def stage2(x):
        if x == 3:
            raise Boom("writer died")

    pipe = pl.StagePipeline([lambda x: x, stage2], depth=1,
                            on_drop=dropped.append)
    with pytest.raises(Boom):
        for i in range(100):
            pipe.submit(i)
    assert pipe.failed
    # close(abort=True) after a caller-side raise must not re-raise
    pipe.close(abort=True)
    # items queued behind the failure were handed to on_drop
    assert dropped


def test_stage_pipeline_close_reraises_tail_error():
    class Boom(RuntimeError):
        pass

    def stage(x):
        raise Boom("late failure")

    pipe = pl.StagePipeline([stage], depth=4)
    pipe.submit(1)      # may or may not raise here (timing)
    with pytest.raises(Boom):
        pipe.close()


def test_staging_pool_is_shared_per_width():
    a = pl.staging_pool(12345)
    b = pl.staging_pool(12345)
    assert a is b and a.width == 12345


# ---------------------------------------------------------------------------
# pipelined PUT correctness
# ---------------------------------------------------------------------------

def test_pipelined_put_roundtrip_sizes(tmp_path, monkeypatch):
    """Zero-byte, single-block, short-last-block and multi-batch
    objects through the pipelined loop (batch cap shrunk so small
    fixtures span many batches)."""
    monkeypatch.setattr(engine_mod, "ENCODE_BATCH_BLOCKS", 2)
    eng = make_engine(tmp_path)
    for size in [0, 1, 100, BLOCK - 1, BLOCK, BLOCK + 1, 2 * BLOCK,
                 5 * BLOCK + 12345]:
        data = payload(size, seed=size)
        oi = put_pipelined(eng, f"o{size}", data)
        assert oi.size == size
        import hashlib
        assert oi.etag == hashlib.md5(data).hexdigest()
        _, it = eng.get_object("b", f"o{size}")
        assert b"".join(it) == data, size
    # known-size exact batch multiple: EOF short-circuit (no probe
    # buffer acquired for a stream that is already fully staged)
    data = payload(4 * BLOCK, seed=99)
    eng.put_object("b", "exact", data)
    _, it = eng.get_object("b", "exact")
    assert b"".join(it) == data


@pytest.mark.parametrize("block_size", [BLOCK, BLOCK + 3])
def test_pipelined_shards_byte_identical_to_serial(tmp_path,
                                                   monkeypatch,
                                                   block_size):
    """The pipeline must not change a single byte on disk: same object
    through the serial and pipelined loops -> identical part files on
    every drive (klauspost-identical shard bytes + identical bitrot
    framing). The BLOCK+3 geometry has a nonzero pad tail
    (block_size % k != 0) and the pipelined engine puts a decoy object
    FIRST, so the comparison covers staging-buffer reuse: a stale pad
    tail would leak the decoy's bytes into the second object's
    shards."""
    import glob
    monkeypatch.setattr(engine_mod, "ENCODE_BATCH_BLOCKS", 2)
    fmts = new_format_erasure_v3(1, NDISKS)

    def mk(sub):
        disks = []
        for j in range(NDISKS):
            d = XLStorage(str(tmp_path / f"{sub}d{j}"))
            d.write_format(fmts[0][j])
            disks.append(d)
        e = ErasureSetObjects(disks, K, M, block_size=block_size)
        e.make_bucket("b")
        return e

    data = payload(7 * block_size + 4321, seed=42)

    monkeypatch.setattr(pl, "ENABLED", False)
    e_serial = mk("s")
    e_serial.put_object("b", "obj", data)

    monkeypatch.setattr(pl, "ENABLED", True)
    e_pipe = mk("p")
    put_pipelined(e_pipe, "decoy",
                  bytes([0xAA]) * (6 * block_size))  # dirty the ring
    put_pipelined(e_pipe, "obj", data)

    for j in range(NDISKS):
        parts_s = sorted(glob.glob(
            str(tmp_path / f"sd{j}" / "b" / "obj" / "*" / "part.1")))
        parts_p = sorted(glob.glob(
            str(tmp_path / f"pd{j}" / "b" / "obj" / "*" / "part.1")))
        assert len(parts_s) == len(parts_p) == 1, j
        with open(parts_s[0], "rb") as f:
            want = f.read()
        with open(parts_p[0], "rb") as f:
            got = f.read()
        assert got == want, f"drive {j} shard bytes diverge"


def test_pipeline_off_escape_hatch(tmp_path, monkeypatch):
    monkeypatch.setattr(pl, "ENABLED", False)
    called = []
    orig = ErasureSetObjects._encode_stream_serial

    def spy(self, *a, **kw):
        called.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(ErasureSetObjects, "_encode_stream_serial", spy)
    eng = make_engine(tmp_path)
    data = payload(3 * BLOCK + 7)
    eng.put_object("b", "o", io.BytesIO(data), size=-1)
    assert called                      # serial loop selected
    _, it = eng.get_object("b", "o")
    assert b"".join(it) == data


def test_single_batch_stream_stays_serial(tmp_path, monkeypatch):
    """A stream that fits one encode batch has nothing to overlap —
    the known-size heuristic keeps it on the serial loop."""
    called = []
    orig = ErasureSetObjects._encode_stream_pipelined

    def spy(self, *a, **kw):
        called.append(1)
        return orig(self, *a, **kw)

    monkeypatch.setattr(ErasureSetObjects, "_encode_stream_pipelined",
                        spy)
    eng = make_engine(tmp_path)
    eng.put_object("b", "small", payload(BLOCK))       # known size
    assert not called
    eng.put_object("b", "big",
                   payload(engine_mod.ENCODE_BATCH_BLOCKS * BLOCK + 1))
    assert called


def test_pipelined_put_quorum_error_propagates(tmp_path, monkeypatch):
    """Writer death below quorum mid-stream fails the PUT with the
    REAL quorum error (fail-fast through the pipeline), and every
    staging buffer returns to the ring."""
    monkeypatch.setattr(engine_mod, "ENCODE_BATCH_BLOCKS", 2)
    eng = make_engine(tmp_path, naughty=True)
    for j in range(3):                  # 3 dead > m=2 tolerable
        eng.disks[j].fail_verbs["append_file"] = serr.FaultyDisk("dead")
        eng.disks[j].fail_verbs["create_file"] = serr.FaultyDisk("dead")
    width = 2 * K * (-(-BLOCK // K))
    pool = pl.staging_pool(width)
    with pytest.raises(api_errors.InsufficientWriteQuorum):
        put_pipelined(eng, "doomed", payload(6 * BLOCK))
    # buffers all recycled (the wreck didn't leak the ring) — and all
    # DISTINCT: a double pool.put of one buffer would hand the same
    # bytearray to two later streams (silent cross-stream corruption).
    # The pool allocates lazily, so "all recycled" = every CREATED
    # buffer is back in the queue.
    import time as _t
    deadline = _t.monotonic() + 5
    while pool._q.qsize() < pool._created and _t.monotonic() < deadline:
        _t.sleep(0.01)
    assert pool._created > 0
    assert pool._q.qsize() == pool._created
    held = [pool.get(timeout=1.0) for _ in range(pool.capacity)]
    try:
        assert len({id(b) for b in held}) == pool.capacity
    finally:
        for b in held:
            pool.put(b)


def test_pipelined_put_records_overlap_stats(tmp_path, monkeypatch):
    monkeypatch.setattr(engine_mod, "ENCODE_BATCH_BLOCKS", 2)
    eng = make_engine(tmp_path)
    before = pl.STATS.snapshot()
    put_pipelined(eng, "o", payload(6 * BLOCK))
    after = pl.STATS.snapshot()
    assert after["put_streams"] == before["put_streams"] + 1
    assert after["put_batches"] >= before["put_batches"] + 3
    assert after["put_wall_s"] > before["put_wall_s"]


# ---------------------------------------------------------------------------
# GET lookahead prefetch
# ---------------------------------------------------------------------------

def test_get_prefetch_multigroup_roundtrip(tmp_path, monkeypatch):
    """An object spanning several read groups roundtrips with the
    lookahead on, and the prefetch counters move."""
    monkeypatch.setattr(engine_mod, "GET_BATCH_BLOCKS", 2)
    eng = make_engine(tmp_path)
    data = payload(9 * BLOCK + 17, seed=9)
    eng.put_object("b", "o", data)
    before = pl.STATS.snapshot()
    _, it = eng.get_object("b", "o")
    assert b"".join(it) == data
    after = pl.STATS.snapshot()
    assert after["get_prefetched"] > before["get_prefetched"]


def test_get_prefetch_degraded_read_reconstructs(tmp_path, monkeypatch):
    """Hedged-read degradation under the lookahead: two drives failing
    shard reads mid-GET still reconstruct every group, byte-identical,
    and flag the object for heal."""
    monkeypatch.setattr(engine_mod, "GET_BATCH_BLOCKS", 2)
    eng = make_engine(tmp_path, naughty=True)
    data = payload(8 * BLOCK + 99, seed=11)
    eng.put_object("b", "o", data)
    flagged = []
    eng.on_degraded_read = lambda b, o: flagged.append((b, o))
    for j in (0, 1):
        eng.disks[j].fail_verbs["read_file_stream"] = \
            serr.FaultyDisk("dead reader")
    _, it = eng.get_object("b", "o")
    assert b"".join(it) == data
    assert flagged


def test_get_prefetch_off_is_serial(tmp_path, monkeypatch):
    monkeypatch.setattr(engine_mod, "GET_BATCH_BLOCKS", 2)
    monkeypatch.setattr(pl, "ENABLED", False)
    eng = make_engine(tmp_path)
    data = payload(6 * BLOCK, seed=3)
    eng.put_object("b", "o", data)
    before = pl.STATS.snapshot()
    _, it = eng.get_object("b", "o")
    assert b"".join(it) == data
    after = pl.STATS.snapshot()
    assert after["get_prefetched"] == before["get_prefetched"]


# ---------------------------------------------------------------------------
# OBD fault counters
# ---------------------------------------------------------------------------

def test_obd_surfaces_drive_fault_counters(tmp_path):
    from minio_tpu.utils.obd import drive_fault_counters, local_obd
    eng = make_engine(tmp_path, naughty=True)
    eng.disks[0].fail_verbs["append_file"] = serr.FaultyDisk("x")
    try:
        eng.put_object("b", "o", payload(BLOCK))
    except api_errors.ObjectApiError:
        pass
    entries = drive_fault_counters(eng.disks)
    assert len(entries) == NDISKS
    assert all("faults" in e for e in entries)        # NaughtyDisk stats
    assert entries[0]["faults"]["total_ops"] > 0
    out = local_obd([], storage_drives=eng.disks)
    assert len(out["drive_faults"]) == NDISKS
    # a None slot reports offline instead of crashing the bundle
    entries = drive_fault_counters([None] + list(eng.disks[1:]))
    assert entries[0]["online"] is False


def test_obd_surfaces_transport_counters():
    from minio_tpu.distributed.storage_rpc import RemoteStorage
    from minio_tpu.utils.obd import drive_fault_counters
    rs = RemoteStorage("127.0.0.1", 1, "/tmp/none", "ak", "sk",
                       timeout=0.2)
    with pytest.raises(serr.StorageError):
        rs.list_vols()
    entries = drive_fault_counters([rs])
    t = entries[0]["transport"]
    assert t["calls"] >= 1 and t["net_errors"] >= 1
    assert t["offline_trips"] == 1 and t["online"] is False
    rs.rc.close()


def test_staging_ring_sized_from_admission_budget(monkeypatch):
    """configure_pool_buffers() derives the ring capacity from the
    RAM-gated admission budget (~2 buffers per admitted stream) for
    rings created after boot; the env knob pins it; tiny budgets keep
    the floor (ROADMAP PR 2 follow-up)."""
    from minio_tpu.parallel import pipeline as pl
    old = pl.POOL_BUFFERS
    try:
        monkeypatch.setattr(pl, "_POOL_ENV_SET", False)
        assert pl.configure_pool_buffers(24) == 48
        assert pl.POOL_BUFFERS == 48
        pool = pl.staging_pool(48 * 1024 + 1)   # fresh width -> new ring
        assert pool.capacity == 48
        assert pl.configure_pool_buffers(1) == 4          # floor
        # with MINIO_TPU_PIPELINE_POOL set, the operator's value wins
        monkeypatch.setattr(pl, "_POOL_ENV_SET", True)
        pl.POOL_BUFFERS = 7
        assert pl.configure_pool_buffers(100) == 7
    finally:
        pl.POOL_BUFFERS = old
