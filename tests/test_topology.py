"""Topology-plane tests: placement epochs (persisted pool states),
write routing around draining/suspended pools, newest-wins dual-read,
online expansion, and the resumable background rebalancer — including
the end-to-end decommission acceptance flow (drain a pool while GETs
interleave, kill/resume mid-drain from the checkpoint)."""

from __future__ import annotations

import threading
import time

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions
from minio_tpu.object.rebalance import Rebalancer
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.topology import (POOL_ACTIVE, POOL_DRAINING,
                                       POOL_SUSPENDED, TopologyError,
                                       TopologyMap, TopologyStore)
from minio_tpu.storage.xl_storage import MINIO_META_BUCKET
from minio_tpu.utils import telemetry

BLOCK = 1 << 16
NEVER_BUSY = dict(busy_fn=lambda: False, throttle_s=0.001)
# version ids are serialized as UUID bytes in xl.meta
VID1 = "00000000-0000-4000-8000-000000000001"
VID2 = "00000000-0000-4000-8000-000000000002"
VIDM = "00000000-0000-4000-8000-00000000000f"


def make_zone(tmp_path, tag: str, enable_mrf: bool = False) -> ErasureSets:
    return ErasureSets.from_drives(
        [str(tmp_path / f"{tag}d{i}") for i in range(4)], 1, 4, 2,
        block_size=BLOCK, enable_mrf=enable_mrf)


@pytest.fixture()
def pools(tmp_path):
    zz = ErasureServerSets([make_zone(tmp_path, "p0"),
                            make_zone(tmp_path, "p1")])
    zz.make_bucket("b")
    yield zz
    zz.close()


def holders(zz, bucket, name):
    return [i for i, z in enumerate(zz.server_sets)
            if z.has_object_versions(bucket, name)]


def wait_status(zz, want: str, timeout: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = zz.rebalance_status().get("rebalance", {})
        if st.get("status") == want:
            return st
        if st.get("status") == "failed":
            raise AssertionError(f"rebalance failed: {st}")
        time.sleep(0.05)
    raise AssertionError(
        f"rebalance never reached {want!r}: {zz.rebalance_status()}")


# ---------------------------------------------------------------------------
# placement epochs
# ---------------------------------------------------------------------------

def test_topology_map_transitions():
    tm = TopologyMap(3)
    assert tm.epoch == 0 and tm.write_pools() == [0, 1, 2]
    assert tm.set_state(1, POOL_DRAINING) == 1
    assert tm.write_pools() == [0, 2]
    assert tm.draining_pools() == [1]
    # idempotent transition does not burn an epoch
    assert tm.set_state(1, POOL_DRAINING) == 1
    assert tm.set_state(2, POOL_SUSPENDED) == 2
    # the LAST active pool can never be demoted
    with pytest.raises(TopologyError):
        tm.set_state(0, POOL_DRAINING)
    with pytest.raises(TopologyError):
        tm.set_state(7, POOL_ACTIVE)
    with pytest.raises(TopologyError):
        tm.set_state(0, "bogus")
    assert tm.set_state(1, POOL_ACTIVE) == 3


def test_epoch_persists_and_reloads(pools):
    zz = pools
    epoch = zz.set_pool_state(0, POOL_SUSPENDED)
    assert epoch == 1
    # a fresh layer over the same zones recovers the newest epoch
    zz2 = ErasureServerSets(zz.server_sets)
    assert zz2.topology.epoch == 1
    assert zz2.topology.state(0) == POOL_SUSPENDED
    assert zz2.topology.state(1) == POOL_ACTIVE
    # highest epoch wins even when one pool missed the update: write a
    # STALE doc into pool 1 only
    stale = TopologyMap(2)
    import json
    zz.server_sets[1].put_object(
        MINIO_META_BUCKET, "topology/pools.json",
        json.dumps(stale.to_dict()).encode())
    zz.server_sets[0].put_object(
        MINIO_META_BUCKET, "topology/pools.json",
        json.dumps({"epoch": 5, "pools": ["active", "draining"]}
                   ).encode())
    zz3 = ErasureServerSets(zz.server_sets)
    assert zz3.topology.epoch == 5
    assert zz3.topology.state(1) == POOL_DRAINING


def test_writes_route_only_to_active(pools):
    zz = pools
    zz.set_pool_state(0, POOL_DRAINING)
    for i in range(8):
        zz.put_object("b", f"o-{i}", b"x" * 100)
        assert holders(zz, "b", f"o-{i}") == [1]
    # multipart sessions open in active pools only
    uid = zz.new_multipart_upload("b", "mp")
    assert zz._zone_of_upload("b", "mp", uid) is zz.server_sets[1]
    zz.abort_multipart_upload("b", "mp", uid)
    # overwrite of an object held by the DRAINING pool lands active,
    # and the newest-wins read serves the new bytes
    zz.server_sets[0].put_object("b", "held", b"old-bytes")
    zz.put_object("b", "held", b"new-bytes!")
    assert sorted(holders(zz, "b", "held")) == [0, 1]
    _, it = zz.get_object("b", "held")
    assert b"".join(it) == b"new-bytes!"
    assert zz.get_object_info("b", "held").size == len(b"new-bytes!")


def test_newest_marker_shadows_older_data(pools):
    zz = pools
    zz.server_sets[0].put_object("b", "o", b"payload")
    time.sleep(0.01)
    # a NEWER delete marker in the other pool must shadow the data copy
    zz.server_sets[1].put_delete_marker("b", "o", VIDM)
    with pytest.raises(api_errors.ObjectNotFound):
        zz.get_object_info("b", "o")
    with pytest.raises(api_errors.ObjectNotFound):
        zz.get_object("b", "o")


def test_unversioned_delete_purges_every_pool(pools):
    zz = pools
    zz.server_sets[0].put_object("b", "dup", b"v-old")
    zz.server_sets[1].put_object("b", "dup", b"v-new")
    zz.delete_object("b", "dup")
    assert holders(zz, "b", "dup") == []
    with pytest.raises(api_errors.ObjectNotFound):
        zz.get_object_info("b", "dup")


def test_add_pool_online_expansion(tmp_path):
    zz = ErasureServerSets([make_zone(tmp_path, "p0")])
    zz.make_bucket("b")
    zz.put_object("b", "pre", b"before-expansion")
    try:
        idx = zz.add_pool(make_zone(tmp_path, "p1"))
        assert idx == 1
        assert zz.topology.epoch == 1
        assert len(zz.topology) == 2
        # namespace replicated onto the new pool
        assert zz.server_sets[1].bucket_exists("b")
        # overwrite affinity: the object's history stays in pool 0
        zz.put_object("b", "pre", b"after-expansion!")
        assert holders(zz, "b", "pre") == [0]
        _, it = zz.get_object("b", "pre")
        assert b"".join(it) == b"after-expansion!"
        # the persisted epoch doc reaches both pools
        zz2 = ErasureServerSets(zz.server_sets)
        assert zz2.topology.epoch == 1 and len(zz2.topology) == 2
    finally:
        zz.close()


# ---------------------------------------------------------------------------
# decommission + rebalance
# ---------------------------------------------------------------------------

def test_last_active_pool_cannot_drain(tmp_path):
    zz = ErasureServerSets([make_zone(tmp_path, "solo")])
    try:
        with pytest.raises(TopologyError):
            zz.start_decommission(0)
    finally:
        zz.close()


def test_decommission_end_to_end(pools):
    """The acceptance flow: 2 pools -> drain pool 0 with interleaved
    GETs -> everything readable throughout, pool 0 empty, status
    complete, version history + markers preserved."""
    zz = pools
    datas = {}
    for i in range(6):
        name = f"e2e-{i}"
        data = bytes([i]) * (BLOCK + 137 * i)
        zz.server_sets[i % 2].put_object("b", name, data)
        datas[name] = data
    # a versioned object with two versions and a non-latest marker:
    # v1, marker, then v2 (ids must survive the move)
    z0 = zz.server_sets[0]
    z0.put_object("b", "ver", b"v1-bytes",
                  opts=PutOptions(versioned=True, version_id=VID1))
    time.sleep(0.01)
    z0.delete_object("b", "ver", versioned=True)
    time.sleep(0.01)
    z0.put_object("b", "ver", b"v2-bytes!",
                  opts=PutOptions(versioned=True, version_id=VID2))
    datas["ver"] = b"v2-bytes!"

    stop_reads = threading.Event()
    read_failures: list = []

    def reader():
        while not stop_reads.is_set():
            for name, data in datas.items():
                try:
                    _, it = zz.get_object("b", name)
                    if b"".join(it) != data:
                        read_failures.append((name, "byte mismatch"))
                except Exception as e:  # noqa: BLE001 — asserted below
                    read_failures.append((name, repr(e)))

    t = threading.Thread(target=reader)
    t.start()
    try:
        out = zz.start_decommission(0, checkpoint_every=2, **NEVER_BUSY)
        assert out["status"] == "draining"
        assert zz.topology.state(0) == POOL_DRAINING
        st = wait_status(zz, "complete")
    finally:
        stop_reads.set()
        t.join()
    assert not read_failures, read_failures[:5]
    # pool 0 held e2e-0/2/4 and "ver" — 4 object names moved
    assert st["objects_moved"] == 4
    assert st["objects_failed"] == 0
    # pool 0 holds nothing movable anymore
    assert zz.server_sets[0].list_object_versions("b", max_keys=10)[0] == []
    for name, data in datas.items():
        assert holders(zz, "b", name) == [1], name
        _, it = zz.get_object("b", name)
        assert b"".join(it) == data
    vers = [(v.version_id, v.delete_marker, v.mod_time)
            for v in zz.server_sets[1].list_object_versions("b", "ver")[0]
            if v.name == "ver"]
    assert len(vers) == 3
    assert {v[0] for v in vers} >= {VID1, VID2}
    assert any(v[1] for v in vers)          # the marker moved too
    # moves preserved mod times (newest is still vid-2)
    assert vers[0][0] == VID2 and not vers[0][1]
    # rebalance progress metrics counted the work
    snap = telemetry.REGISTRY.snapshot("minio_tpu_rebalance")
    moved = snap["minio_tpu_rebalance_objects_total"].get("pool=0", 0)
    assert moved >= 4           # version moves counted (≥ names moved)


def test_rebalance_resumes_from_checkpoint(pools):
    zz = pools
    for i in range(10):
        zz.server_sets[0].put_object("b", f"r-{i:02d}", b"y" * 200)
    zz.set_pool_state(0, POOL_DRAINING)

    moves = 0

    def busy():
        nonlocal moves
        moves += 1
        if moves == 5:
            reb.stop()          # kill mid-drain (throttle runs
        return False            # before each object's move)

    reb = Rebalancer(zz, 0, checkpoint_every=1, busy_fn=busy)
    reb.start()
    deadline = time.monotonic() + 30
    while reb.running() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not reb.running()
    first = reb.status()
    assert first["status"] == "stopped"
    assert 0 < first["objects_moved"] < 10
    # the persisted checkpoint carries the marker
    ckpt = Rebalancer.load_checkpoint(zz, 0)
    assert ckpt is not None and ckpt["marker"]

    # a NEW rebalancer (fresh process) resumes from the checkpoint
    reb2 = Rebalancer(zz, 0, resume=True, checkpoint_every=1,
                      **NEVER_BUSY)
    assert reb2.state.get("resumed")
    assert reb2.state["marker"] == ckpt["marker"]
    zz._rebalancer = reb2
    reb2.start()
    st = wait_status(zz, "complete")
    # it finished the job without redoing the first instance's moves
    # (the one object interrupted MID-move may be finished — and so
    # counted — by both instances)
    assert 10 <= st["objects_moved"] <= 11
    assert zz.server_sets[0].list_object_versions("b", max_keys=20)[0] == []
    for i in range(10):
        assert holders(zz, "b", f"r-{i:02d}") == [1]


def test_rebalance_throttle_backs_off_on_occupancy(pools):
    zz = pools
    calls = []
    reb = Rebalancer(zz, 0, busy_fn=lambda: calls.append(1) or True,
                     throttle_s=0.001)
    t0 = time.monotonic()
    reb._throttle()
    from minio_tpu.object import rebalance as rmod
    assert len(calls) == rmod.BACKOFF_TRIES     # polled, then proceeded
    assert time.monotonic() - t0 < 5.0
    # not busy: no sleep at all
    calls.clear()
    reb2 = Rebalancer(zz, 0, busy_fn=lambda: calls.append(1) or False)
    reb2._throttle()
    assert len(calls) == 1


def test_cancel_returns_pool_to_active(pools):
    zz = pools
    for i in range(4):
        zz.server_sets[0].put_object("b", f"c-{i}", b"z" * 100)
    zz.start_decommission(0, busy_fn=lambda: True, throttle_s=0.2)
    out = zz.cancel_rebalance()
    assert out["status"] == "canceled"
    assert zz.topology.state(0) == POOL_ACTIVE
    assert zz.topology.write_pools() == [0, 1]


def test_meta_bucket_objects_migrate_but_internals_stay(pools):
    zz = pools
    # a config-plane object (written through the object layer) on the
    # draining pool must migrate; the topology doc itself must not
    zz.server_sets[0].put_object(MINIO_META_BUCKET, "config/test.json",
                                 b'{"k":"v"}')
    zz.set_pool_state(0, POOL_DRAINING)
    reb = Rebalancer(zz, 0, **NEVER_BUSY)
    zz._rebalancer = reb
    reb.start()
    wait_status(zz, "complete")
    _, it = zz.server_sets[1].get_object(MINIO_META_BUCKET,
                                         "config/test.json")
    assert b"".join(it) == b'{"k":"v"}'
    with pytest.raises(api_errors.ObjectNotFound):
        zz.server_sets[0].get_object_info(MINIO_META_BUCKET,
                                          "config/test.json")
    # the per-pool topology doc is still on pool 0 (deliberately)
    zz.server_sets[0].get_object_info(MINIO_META_BUCKET,
                                      "topology/pools.json")


# ---------------------------------------------------------------------------
# DiskMonitor covers pools added after boot (tiering-PR satellite)
# ---------------------------------------------------------------------------

def test_disk_monitor_covers_post_boot_pool(tmp_path):
    """A drive killed in a pool appended AFTER the monitor started is
    re-admitted and healed exactly like a boot-time one: add_pool
    registers the new pool's drive slots with the running monitor."""
    import shutil
    from minio_tpu.object.background import DiskMonitor
    from minio_tpu.storage.xl_storage import XLStorage

    zz = ErasureServerSets([make_zone(tmp_path, "p0")])
    zz.make_bucket("b")
    mon = DiskMonitor(zz.server_sets[0], interval=3600)
    try:
        # online expansion, then register the new pool with the monitor
        # (what ClusterNode.add_pool does)
        pool1 = make_zone(tmp_path, "p1")
        zz.add_pool(pool1)
        mon.add_pool(pool1)

        # land an object in the NEW pool and remember its bytes
        zz.set_pool_state(0, POOL_SUSPENDED)
        payload = b"post-boot pool data " * 5000
        zz.put_object("b", "obj", payload)
        assert zz.server_sets[1].has_object_versions("b", "obj")

        # kill one of the post-boot pool's drives outright (wiped disk)
        victim = str(tmp_path / "p1d2")
        shutil.rmtree(victim)
        assert mon.scan_once() == 1          # re-admitted + formatted
        assert mon.healed_slots              # swept as a fresh drive

        # the wiped drive carries a valid format for ITS pool again
        fmt = XLStorage(victim).read_format()
        assert fmt.id == pool1.deployment_id
        assert fmt.this in [u for row in pool1.format_ref.sets
                            for u in row]

        # healed: the object reads back whole, and a second scan is
        # steady-state for BOTH pools
        _, stream = zz.get_object("b", "obj")
        assert b"".join(stream) == payload
        assert mon.scan_once() == 0
    finally:
        mon.close()
        zz.close()


# ---------------------------------------------------------------------------
# decommission drains LIVE multipart sessions (carried-over item 6)
# ---------------------------------------------------------------------------

def test_decommission_migrates_live_multipart_session(pools):
    """A session in flight on the draining pool is actively migrated —
    metadata, uploaded parts and the client-held uploadID all survive,
    the drain completes without waiting the client out, and the upload
    finishes normally against the new pool."""
    zz = pools
    part = b"p" * (6 << 20)                 # > MIN_PART_SIZE
    uid = zz.new_multipart_upload("b", "mpu-live")
    src = zz._zone_index_of_upload("b", "mpu-live", uid)
    p1 = zz.put_object_part("b", "mpu-live", uid, 1, part, len(part))

    zz.start_decommission(src, mpu_grace_s=0.0, **NEVER_BUSY)
    wait_status(zz, "complete")
    # the session left the draining pool, same uploadID
    dst = zz._zone_index_of_upload("b", "mpu-live", uid)
    assert dst != src
    listed = zz.list_object_parts("b", "mpu-live", uid)
    assert [(p.part_number, p.etag, p.size) for p in listed] == \
        [(1, p1.etag, len(part))]
    st = zz.rebalance_status()["rebalance"]
    assert st["mpu_migrated"] >= 1 and st["mpu_failed"] == 0

    # the client finishes the upload with no idea anything moved
    from minio_tpu.object.multipart import CompletePart
    part2 = b"q" * (1 << 20)
    p2 = zz.put_object_part("b", "mpu-live", uid, 2, part2, len(part2))
    info = zz.complete_multipart_upload(
        "b", "mpu-live", uid,
        [CompletePart(1, p1.etag), CompletePart(2, p2.etag)])
    assert info.size == len(part) + len(part2)
    got_info, stream = zz.get_object("b", "mpu-live")
    body = b"".join(stream)
    assert body == part + part2
    # the committed object never landed in the drained pool
    assert holders(zz, "b", "mpu-live") == [dst]


def test_draining_pool_refuses_new_parts_via_migration(pools):
    """Before the rebalancer even reaches the session, a part-write
    aimed at a draining pool migrates the session to an active pool
    and lands there (draining pools stop accepting NEW parts)."""
    zz = pools
    part = b"x" * (6 << 20)
    uid = zz.new_multipart_upload("b", "mpu-guard")
    src = zz._zone_index_of_upload("b", "mpu-guard", uid)
    p1 = zz.put_object_part("b", "mpu-guard", uid, 1, part, len(part))
    # flip the pool draining WITHOUT starting the walker
    zz.set_pool_state(src, POOL_DRAINING)
    try:
        part2 = b"y" * (1 << 20)
        zz.put_object_part("b", "mpu-guard", uid, 2, part2, len(part2))
        dst = zz._zone_index_of_upload("b", "mpu-guard", uid)
        assert dst != src
        listed = zz.list_object_parts("b", "mpu-guard", uid)
        assert [p.part_number for p in listed] == [1, 2]
        assert listed[0].etag == p1.etag
    finally:
        zz.set_pool_state(src, POOL_ACTIVE)


def test_mpu_migration_crash_window_converges(pools):
    """A crash between the parts copy and the source abort leaves the
    session in two pools. Clients continue on the writable target; a
    re-run copies only what the target lacks and never overwrites a
    newer client part; and once the client COMPLETES the upload, the
    sweep purges the stale source leftover instead of resurrecting a
    zombie session."""
    zz = pools
    part = b"m" * (6 << 20)
    uid = zz.new_multipart_upload("b", "mpu-crash")
    src = zz._zone_index_of_upload("b", "mpu-crash", uid)
    p1 = zz.put_object_part("b", "mpu-crash", uid, 1, part, len(part))
    zz.set_pool_state(src, POOL_DRAINING)
    try:
        # crash the migration right before the source abort
        src_zone = zz.server_sets[src]
        real_abort = src_zone.abort_multipart_upload
        calls = []

        def dying_abort(*a, **kw):
            calls.append(a)
            raise RuntimeError("crash before source abort")

        src_zone.abort_multipart_upload = dying_abort
        with pytest.raises(RuntimeError):
            zz.migrate_upload("b", "mpu-crash", uid, source=src)
        src_zone.abort_multipart_upload = real_abort
        # dual-homed now; the writable twin owns client traffic
        dst = zz._zone_index_of_upload("b", "mpu-crash", uid)
        assert dst != src and zz.topology.can_write(dst)

        # client overwrites part 1 on the target (newer bytes)
        newer = b"n" * (6 << 20)
        p1b = zz.put_object_part("b", "mpu-crash", uid, 1, newer,
                                 len(newer))
        # sweep re-run: resumes at the existing twin, target parts win
        assert zz.migrate_upload("b", "mpu-crash", uid,
                                 source=src) == dst
        listed = zz.list_object_parts("b", "mpu-crash", uid)
        assert [(p.part_number, p.etag) for p in listed] == \
            [(1, p1b.etag)]
        # source leftover gone
        with pytest.raises(api_errors.InvalidUploadID):
            zz.server_sets[src].list_object_parts("b", "mpu-crash",
                                                  uid, max_parts=1)

        # consumed-leftover path: crash again, then the client
        # completes on the target — the sweep must purge, not resurrect
        uid2 = zz.new_multipart_upload("b", "mpu-done")
        # route the session onto the ACTIVE pool (dst) so the drained
        # pool is its migration target? No: create on active, drain
        # that one instead.
        s2 = zz._zone_index_of_upload("b", "mpu-done", uid2)
        q1 = zz.put_object_part("b", "mpu-done", uid2, 1, part,
                                len(part))
        if s2 != src:
            zz.set_pool_state(src, POOL_ACTIVE)
            zz.set_pool_state(s2, POOL_DRAINING)
        z2 = zz.server_sets[s2]
        real_abort2 = z2.abort_multipart_upload
        z2.abort_multipart_upload = dying_abort
        with pytest.raises(RuntimeError):
            zz.migrate_upload("b", "mpu-done", uid2, source=s2)
        z2.abort_multipart_upload = real_abort2
        from minio_tpu.object.multipart import CompletePart
        zz.complete_multipart_upload("b", "mpu-done", uid2,
                                     [CompletePart(1, q1.etag)])
        # the stale source leftover is purged as consumed
        with pytest.raises(api_errors.InvalidUploadID):
            zz.migrate_upload("b", "mpu-done", uid2, source=s2)
        with pytest.raises(api_errors.InvalidUploadID):
            z2.list_object_parts("b", "mpu-done", uid2, max_parts=1)
        # and the completed object reads back whole
        _info, stream = zz.get_object("b", "mpu-done")
        assert b"".join(stream) == part
    finally:
        for i in range(len(zz.server_sets)):
            if zz.topology.state(i) != POOL_ACTIVE:
                zz.set_pool_state(i, POOL_ACTIVE)
