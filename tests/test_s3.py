"""S3 HTTP frontend tests: signed requests end-to-end against a live
server over a real erasure object layer (the reference's
ExecObjectLayerAPITest pattern, cmd/test-utils_test.go:1812)."""

from __future__ import annotations

import hashlib
import http.client
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("testadminkey", "testadminsecretkey")
REGION = "us-east-1"


class S3TestClient:
    """Minimal SigV4-signing HTTP client."""

    def __init__(self, host: str, port: int,
                 creds: Credentials = CREDS):
        self.host, self.port, self.creds = host, port, creds

    def request(self, method: str, path: str, query: dict | None = None,
                body: bytes = b"", headers: dict | None = None,
                sign: bool = True, streaming: bool = False):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"{self.host}:{self.port}"
        if sign:
            payload_hash = hashlib.sha256(body).hexdigest()
            hdrs = sig.sign_v4(method, urllib.parse.quote(path), query,
                               hdrs, payload_hash, self.creds, REGION)
        conn = http.client.HTTPConnection(self.host, self.port, timeout=60)
        url = urllib.parse.quote(path) + (f"?{qs}" if qs else "")
        conn.request(method, url, body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        out_headers = {k.lower(): v for k, v in resp.getheaders()}
        conn.close()
        return resp.status, out_headers, data


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("s3drives")
    drives = [str(root / f"d{i}") for i in range(8)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=8,
                                   parity=2, block_size=1 << 18)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    yield srv
    srv.stop()
    sets.close()


@pytest.fixture(scope="module")
def client(server):
    return S3TestClient("127.0.0.1", server.port)


@pytest.fixture(scope="module")
def bucket(client):
    status, _, _ = client.request("PUT", "/testbucket")
    assert status == 200
    return "testbucket"


def test_unauthenticated_rejected(client):
    status, _, body = client.request("GET", "/", sign=False)
    assert status == 403
    assert b"<Error>" in body


def test_bad_signature_rejected(server):
    bad = S3TestClient("127.0.0.1", server.port,
                       Credentials(CREDS.access_key, "wrongsecret000"))
    status, _, body = bad.request("GET", "/")
    assert status == 403
    assert b"SignatureDoesNotMatch" in body


def test_unknown_access_key(server):
    bad = S3TestClient("127.0.0.1", server.port,
                       Credentials("nosuchaccesskey", "whatever12345"))
    status, _, body = bad.request("GET", "/")
    assert status == 403
    assert b"InvalidAccessKeyId" in body


def test_make_and_list_buckets(client, bucket):
    status, headers, body = client.request("GET", "/")
    assert status == 200
    root = ET.fromstring(body)
    names = [e.text for e in root.iter(
        f"{{{ 'http://s3.amazonaws.com/doc/2006-03-01/' }}}Name")]
    assert bucket in names


def test_bucket_lifecycle_of_missing(client):
    status, _, body = client.request("GET", "/nosuchbucket123",
                                     query={"location": ""})
    assert status == 404
    assert b"NoSuchBucket" in body


def test_invalid_bucket_name(client):
    status, _, body = client.request("PUT", "/UPPER_CASE_BAD")
    assert status == 400


def test_head_bucket(client, bucket):
    status, _, _ = client.request("HEAD", f"/{bucket}")
    assert status == 200
    status, _, _ = client.request("HEAD", "/absent-bucket-xyz")
    assert status == 404


def test_put_get_object_roundtrip(client, bucket):
    data = b"hello tpu object store" * 1000
    status, headers, _ = client.request("PUT", f"/{bucket}/obj1",
                                        body=data)
    assert status == 200
    etag = headers["etag"].strip('"')
    assert etag == hashlib.md5(data).hexdigest()

    status, headers, got = client.request("GET", f"/{bucket}/obj1")
    assert status == 200
    assert got == data
    assert headers["etag"].strip('"') == etag
    assert headers["content-length"] == str(len(data))


def test_head_object(client, bucket):
    data = b"head me"
    client.request("PUT", f"/{bucket}/headobj", body=data)
    status, headers, body = client.request("HEAD", f"/{bucket}/headobj")
    assert status == 200
    assert headers["content-length"] == str(len(data))
    assert body == b""


def test_get_missing_object(client, bucket):
    status, _, body = client.request("GET", f"/{bucket}/absent-key")
    assert status == 404
    assert b"NoSuchKey" in body


def test_ranged_get(client, bucket):
    data = bytes(range(256)) * 64
    client.request("PUT", f"/{bucket}/ranged", body=data)
    status, headers, got = client.request(
        "GET", f"/{bucket}/ranged", headers={"Range": "bytes=100-199"})
    assert status == 206
    assert got == data[100:200]
    assert headers["content-range"] == f"bytes 100-199/{len(data)}"
    # suffix range
    status, _, got = client.request(
        "GET", f"/{bucket}/ranged", headers={"Range": "bytes=-50"})
    assert status == 206
    assert got == data[-50:]
    # unsatisfiable
    status, _, _ = client.request(
        "GET", f"/{bucket}/ranged",
        headers={"Range": f"bytes={len(data)}-"})
    assert status == 416


def test_conditional_get(client, bucket):
    data = b"conditional body"
    _, headers, _ = client.request("PUT", f"/{bucket}/cond", body=data)
    etag = headers["etag"]
    status, _, _ = client.request("GET", f"/{bucket}/cond",
                                  headers={"If-None-Match": etag})
    assert status == 304
    status, _, _ = client.request("GET", f"/{bucket}/cond",
                                  headers={"If-Match": '"deadbeef"'})
    assert status == 412


def test_content_md5_verified(client, bucket):
    import base64
    data = b"md5 checked payload"
    good = base64.b64encode(hashlib.md5(data).digest()).decode()
    status, _, _ = client.request("PUT", f"/{bucket}/md5ok", body=data,
                                  headers={"Content-MD5": good})
    assert status == 200
    bad = base64.b64encode(hashlib.md5(b"other").digest()).decode()
    status, _, body = client.request("PUT", f"/{bucket}/md5bad",
                                     body=data,
                                     headers={"Content-MD5": bad})
    assert status == 400


def test_delete_object(client, bucket):
    client.request("PUT", f"/{bucket}/todelete", body=b"x")
    status, _, _ = client.request("DELETE", f"/{bucket}/todelete")
    assert status == 204
    status, _, _ = client.request("GET", f"/{bucket}/todelete")
    assert status == 404
    # deleting a missing key is still 204
    status, _, _ = client.request("DELETE", f"/{bucket}/never-existed")
    assert status == 204


def test_list_objects_v1_and_v2(client, bucket):
    for i in range(3):
        client.request("PUT", f"/{bucket}/list/a{i}", body=b"d")
    client.request("PUT", f"/{bucket}/list/sub/deep", body=b"d")
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"prefix": "list/",
                                            "delimiter": "/"})
    assert status == 200
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    root = ET.fromstring(body)
    keys = [c.find(f"{ns}Key").text for c in root.iter(f"{ns}Contents")]
    prefixes = [p.find(f"{ns}Prefix").text
                for p in root.iter(f"{ns}CommonPrefixes")]
    assert keys == ["list/a0", "list/a1", "list/a2"]
    assert prefixes == ["list/sub/"]

    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"list-type": "2",
                                            "prefix": "list/",
                                            "delimiter": "/"})
    root = ET.fromstring(body)
    assert root.find(f"{ns}KeyCount").text == "4"


def test_multipart_roundtrip(client, bucket):
    status, _, body = client.request("POST", f"/{bucket}/mpobj",
                                     query={"uploads": ""})
    assert status == 200
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    upload_id = ET.fromstring(body).find(f"{ns}UploadId").text

    part1 = b"A" * (5 << 20)
    part2 = b"B" * 1024
    etags = []
    for num, part in ((1, part1), (2, part2)):
        status, headers, _ = client.request(
            "PUT", f"/{bucket}/mpobj",
            query={"partNumber": str(num), "uploadId": upload_id},
            body=part)
        assert status == 200
        etags.append(headers["etag"].strip('"'))

    status, _, body = client.request(
        "GET", f"/{bucket}/mpobj", query={"uploadId": upload_id})
    assert status == 200
    assert body.count(b"<Part>") == 2

    complete = (
        '<CompleteMultipartUpload>'
        + "".join(f"<Part><PartNumber>{n}</PartNumber>"
                  f"<ETag>\"{e}\"</ETag></Part>"
                  for n, e in zip((1, 2), etags))
        + '</CompleteMultipartUpload>').encode()
    status, _, body = client.request(
        "POST", f"/{bucket}/mpobj", query={"uploadId": upload_id},
        body=complete)
    assert status == 200
    assert b"CompleteMultipartUploadResult" in body

    status, _, got = client.request("GET", f"/{bucket}/mpobj")
    assert status == 200
    assert got == part1 + part2


def test_multipart_abort(client, bucket):
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    _, _, body = client.request("POST", f"/{bucket}/abortobj",
                                query={"uploads": ""})
    upload_id = ET.fromstring(body).find(f"{ns}UploadId").text
    client.request("PUT", f"/{bucket}/abortobj",
                   query={"partNumber": "1", "uploadId": upload_id},
                   body=b"data")
    status, _, _ = client.request("DELETE", f"/{bucket}/abortobj",
                                  query={"uploadId": upload_id})
    assert status == 204
    status, _, body = client.request(
        "GET", f"/{bucket}/abortobj", query={"uploadId": upload_id})
    assert status == 404
    assert b"NoSuchUpload" in body


def test_copy_object(client, bucket):
    data = b"copy source data" * 100
    client.request("PUT", f"/{bucket}/copysrc", body=data)
    status, _, body = client.request(
        "PUT", f"/{bucket}/copydst",
        headers={"x-amz-copy-source": f"/{bucket}/copysrc"})
    assert status == 200
    assert b"CopyObjectResult" in body
    status, _, got = client.request("GET", f"/{bucket}/copydst")
    assert got == data


def test_delete_multiple_objects(client, bucket):
    for i in range(3):
        client.request("PUT", f"/{bucket}/bulk{i}", body=b"x")
    doc = ("<Delete>" +
           "".join(f"<Object><Key>bulk{i}</Key></Object>"
                   for i in range(3)) +
           "<Object><Key>bulk-missing</Key></Object></Delete>").encode()
    status, _, body = client.request("POST", f"/{bucket}",
                                     query={"delete": ""}, body=doc)
    assert status == 200
    assert body.count(b"<Deleted>") == 4
    for i in range(3):
        status, _, _ = client.request("GET", f"/{bucket}/bulk{i}")
        assert status == 404


def test_versioning_cycle(client, bucket):
    cfg = (b'<VersioningConfiguration>'
           b'<Status>Enabled</Status></VersioningConfiguration>')
    status, _, _ = client.request("PUT", f"/{bucket}",
                                  query={"versioning": ""}, body=cfg)
    assert status == 200
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"versioning": ""})
    assert status == 200
    assert b"Enabled" in body

    # two PUTs -> two versions
    _, h1, _ = client.request("PUT", f"/{bucket}/vobj", body=b"v1")
    _, h2, _ = client.request("PUT", f"/{bucket}/vobj", body=b"v2")
    v1, v2 = h1.get("x-amz-version-id"), h2.get("x-amz-version-id")
    assert v1 and v2 and v1 != v2

    _, _, got = client.request("GET", f"/{bucket}/vobj")
    assert got == b"v2"
    _, _, got = client.request("GET", f"/{bucket}/vobj",
                               query={"versionId": v1})
    assert got == b"v1"

    # delete -> marker; latest GET 404s, old version still readable
    status, headers, _ = client.request("DELETE", f"/{bucket}/vobj")
    assert status == 204
    assert headers.get("x-amz-delete-marker") == "true"
    status, _, _ = client.request("GET", f"/{bucket}/vobj")
    assert status == 404
    _, _, got = client.request("GET", f"/{bucket}/vobj",
                               query={"versionId": v2})
    assert got == b"v2"

    # list versions shows marker + 2 versions
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"versions": "",
                                            "prefix": "vobj"})
    assert status == 200
    assert body.count(b"<Version>") == 2
    assert body.count(b"<DeleteMarker>") == 1
    # suspend versioning again for later tests
    cfg = (b'<VersioningConfiguration>'
           b'<Status>Suspended</Status></VersioningConfiguration>')
    client.request("PUT", f"/{bucket}", query={"versioning": ""},
                   body=cfg)


def test_bucket_policy_cycle(client, bucket):
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"policy": ""})
    assert status == 404
    policy = (b'{"Version":"2012-10-17","Statement":[{"Effect":"Allow",'
              b'"Principal":{"AWS":["*"]},"Action":["s3:GetObject"],'
              b'"Resource":["arn:aws:s3:::%s/*"]}]}' % bucket.encode())
    status, _, _ = client.request("PUT", f"/{bucket}",
                                  query={"policy": ""}, body=policy)
    assert status == 204
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"policy": ""})
    assert status == 200
    assert b"s3:GetObject" in body
    status, _, _ = client.request("DELETE", f"/{bucket}",
                                  query={"policy": ""})
    assert status == 204


def test_bucket_tagging_cycle(client, bucket):
    doc = (b"<Tagging><TagSet>"
           b"<Tag><Key>team</Key><Value>tpu</Value></Tag>"
           b"</TagSet></Tagging>")
    status, _, _ = client.request("PUT", f"/{bucket}",
                                  query={"tagging": ""}, body=doc)
    assert status == 200
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"tagging": ""})
    assert status == 200
    assert b"<Key>team</Key>" in body and b"<Value>tpu</Value>" in body
    status, _, _ = client.request("DELETE", f"/{bucket}",
                                  query={"tagging": ""})
    assert status == 204


def test_presigned_get(server, client, bucket):
    data = b"presigned content"
    client.request("PUT", f"/{bucket}/presigned", body=data)
    qs = sig.presign_v4("GET", f"/{bucket}/presigned", {}, {
        "host": f"127.0.0.1:{server.port}"}, CREDS, REGION, 600)
    conn = http.client.HTTPConnection("127.0.0.1", server.port)
    conn.request("GET", f"/{bucket}/presigned?{qs}")
    resp = conn.getresponse()
    got = resp.read()
    assert resp.status == 200
    assert got == data
    conn.close()


def test_streaming_signed_put(server, bucket):
    """Streaming chunked V4 upload (aws-chunked payload)."""
    import datetime
    import hashlib as h
    import hmac as hm

    host = f"127.0.0.1:{server.port}"
    path = f"/{bucket}/streamed"
    data = b"S" * 70000
    chunk_size = 65536
    t = datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime(sig.ISO8601_FORMAT)
    date = t.strftime(sig.YYYYMMDD)
    scope = f"{date}/{REGION}/s3/aws4_request"

    decoded_len = len(data)
    chunks = [data[i:i + chunk_size]
              for i in range(0, len(data), chunk_size)] + [b""]
    # encoded length: sum over chunks of header+payload+crlf
    enc_len = 0
    for c in chunks:
        header = f"{len(c):x};chunk-signature={'0' * 64}\r\n"
        enc_len += len(header) + len(c) + 2

    headers = {
        "host": host,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": sig.STREAMING_CONTENT_SHA256,
        "x-amz-decoded-content-length": str(decoded_len),
        "content-length": str(enc_len),
    }
    signed = sorted(["host", "x-amz-content-sha256", "x-amz-date",
                     "x-amz-decoded-content-length"])
    canon = sig.canonical_request("PUT", path, "", headers, signed,
                                  sig.STREAMING_CONTENT_SHA256)
    sts = sig.string_to_sign(canon, amz_date, scope)
    key = sig.signing_key(CREDS.secret_key, date, REGION)
    seed_sig = hm.new(key, sts.encode(), h.sha256).hexdigest()
    headers["authorization"] = (
        f"{sig.SIGN_V4_ALGORITHM} Credential={CREDS.access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={seed_sig}")

    # build chunked body with chained chunk signatures
    body = b""
    prev = seed_sig
    for c in chunks:
        chunk_sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev,
            sig.EMPTY_SHA256, h.sha256(c).hexdigest()])
        csig = hm.new(key, chunk_sts.encode(), h.sha256).hexdigest()
        body += f"{len(c):x};chunk-signature={csig}\r\n".encode()
        body += c + b"\r\n"
        prev = csig
    assert len(body) == enc_len

    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=60)
    conn.request("PUT", path, body=body, headers=headers)
    resp = conn.getresponse()
    resp.read()
    assert resp.status == 200
    conn.close()

    cl = S3TestClient("127.0.0.1", server.port)
    status, _, got = cl.request("GET", path)
    assert status == 200
    assert got == data


def test_object_tagging_cycle(client, bucket):
    client.request("PUT", f"/{bucket}/tagobj", body=b"x")
    doc = (b"<Tagging><TagSet>"
           b"<Tag><Key>k1</Key><Value>v1</Value></Tag>"
           b"</TagSet></Tagging>")
    status, _, _ = client.request("PUT", f"/{bucket}/tagobj",
                                  query={"tagging": ""}, body=doc)
    assert status == 200
    status, _, body = client.request("GET", f"/{bucket}/tagobj",
                                     query={"tagging": ""})
    assert status == 200
    assert b"<Key>k1</Key>" in body
    status, _, _ = client.request("DELETE", f"/{bucket}/tagobj",
                                  query={"tagging": ""})
    assert status == 204


def test_delete_bucket_not_empty_then_empty(client):
    client.request("PUT", "/delbucket")
    client.request("PUT", "/delbucket/obj", body=b"x")
    status, _, body = client.request("DELETE", "/delbucket")
    assert status == 409
    client.request("DELETE", "/delbucket/obj")
    status, _, _ = client.request("DELETE", "/delbucket")
    assert status == 204


def test_keepalive_after_unread_body(server, bucket):
    """An errored PUT whose body the handler never read must not poison
    the next request on the same persistent connection."""
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=30)
    body = b"Z" * 4096
    # unsigned PUT with a body -> 403 before the handler touches rfile
    conn.request("PUT", f"/{bucket}/poison", body=body,
                 headers={"Host": f"127.0.0.1:{server.port}"})
    resp = conn.getresponse()
    assert resp.status == 403
    resp.read()
    # same socket: a signed GET must still parse cleanly
    cl = S3TestClient("127.0.0.1", server.port)
    import urllib.parse as up
    hdrs = sig.sign_v4("GET", "/", {}, {
        "host": f"127.0.0.1:{server.port}"},
        hashlib.sha256(b"").hexdigest(), CREDS, REGION)
    conn.request("GET", "/", headers=hdrs)
    resp = conn.getresponse()
    assert resp.status == 200
    resp.read()
    conn.close()


def test_list_multipart_uploads_reports_keys(client, bucket):
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    _, _, body = client.request("POST", f"/{bucket}/listmp/realkey",
                                query={"uploads": ""})
    upload_id = ET.fromstring(body).find(f"{ns}UploadId").text
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"uploads": ""})
    assert status == 200
    root = ET.fromstring(body)
    entries = {(u.find(f"{ns}Key").text, u.find(f"{ns}UploadId").text)
               for u in root.iter(f"{ns}Upload")}
    assert ("listmp/realkey", upload_id) in entries
    client.request("DELETE", f"/{bucket}/listmp/realkey",
                   query={"uploadId": upload_id})


def test_max_keys_zero(client, bucket):
    status, _, body = client.request("GET", f"/{bucket}",
                                     query={"max-keys": "0"})
    assert status == 200
    assert b"<Contents>" not in body
    assert b"<IsTruncated>false</IsTruncated>" in body


def test_delete_multiple_on_missing_bucket(client):
    doc = b"<Delete><Object><Key>k</Key></Object></Delete>"
    status, _, body = client.request("POST", "/absent-bucket-zz",
                                     query={"delete": ""}, body=doc)
    assert status == 404
    assert b"NoSuchBucket" in body


def test_signed_body_sha_mismatch_rejected(client, bucket):
    """A signed request whose body doesn't match the signed
    x-amz-content-sha256 must be rejected (isReqAuthenticated analog)."""
    policy = (b'{"Version":"2012-10-17","Statement":[{"Effect":"Allow",'
              b'"Principal":{"AWS":["*"]},"Action":["s3:GetObject"],'
              b'"Resource":["arn:aws:s3:::testbucket/*"]}]}')
    # sign over DIFFERENT bytes than we send
    wrong_hash = hashlib.sha256(b"something else entirely").hexdigest()
    hdrs = {"host": f"{client.host}:{client.port}"}
    hdrs = sig.sign_v4("PUT", f"/{bucket}", {"policy": [""]}, hdrs,
                       wrong_hash, client.creds, REGION)
    conn = http.client.HTTPConnection(client.host, client.port, timeout=60)
    conn.request("PUT", f"/{bucket}?policy=", body=policy, headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    assert resp.status == 400
    assert b"XAmzContentSHA256Mismatch" in data


def test_put_bucket_notification_validated_by_plane(client, server):
    """With a NotificationPlane attached, PUT ?notification rejects
    configs naming unknown target ARNs or event names (the reference's
    ErrARNNotification / ErrEventNotification), accepts valid ones, and
    keeps the legacy accept-anything behavior when no plane is wired."""
    status, _, _ = client.request("PUT", "/notifyval")
    assert status == 200
    arn = "arn:minio:sqs::hook1:webhook"

    def xml(target_arn=arn, event="s3:ObjectCreated:*"):
        return (f"<NotificationConfiguration><QueueConfiguration>"
                f"<Queue>{target_arn}</Queue><Event>{event}</Event>"
                f"</QueueConfiguration></NotificationConfiguration>"
                ).encode()

    # no plane attached: any well-formed doc passes (legacy behavior)
    assert server.api.notify is None
    status, _, _ = client.request(
        "PUT", "/notifyval", query={"notification": ""},
        body=xml("arn:minio:sqs::ghost:webhook"))
    assert status == 200

    class _Registry:
        def arns(self):
            return {arn}

    class _Plane:
        registry = _Registry()

    server.api.notify = _Plane()
    try:
        status, _, body = client.request(
            "PUT", "/notifyval", query={"notification": ""},
            body=xml("arn:minio:sqs::ghost:webhook"))
        assert status == 400
        assert b"InvalidArgument" in body and b"ghost" in body

        status, _, body = client.request(
            "PUT", "/notifyval", query={"notification": ""},
            body=xml(event="s3:ObjectTypo:*"))
        assert status == 400
        assert b"InvalidArgument" in body and b"ObjectTypo" in body

        # a rule with no events is structurally invalid, not unknown
        doc = (f"<NotificationConfiguration><QueueConfiguration>"
               f"<Queue>{arn}</Queue>"
               f"</QueueConfiguration></NotificationConfiguration>")
        status, _, body = client.request(
            "PUT", "/notifyval", query={"notification": ""},
            body=doc.encode())
        assert status == 400
        assert b"MalformedXML" in body

        status, _, body = client.request(
            "PUT", "/notifyval", query={"notification": ""},
            body=b"<NotificationConfiguration")
        assert status == 400
        assert b"MalformedXML" in body

        status, _, _ = client.request(
            "PUT", "/notifyval", query={"notification": ""}, body=xml())
        assert status == 200
        status, _, body = client.request(
            "GET", "/notifyval", query={"notification": ""})
        assert status == 200
        assert arn.encode() in body
    finally:
        server.api.notify = None
