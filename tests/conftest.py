"""Test configuration: force an 8-device virtual CPU mesh.

Must run before jax is imported anywhere — pytest imports conftest first.
The driver's multichip dry-run uses the same mechanism
(xla_force_host_platform_device_count), so tests exercise the identical
virtual-mesh path.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
