"""Test configuration: force an 8-device virtual CPU mesh.

The environment ships JAX_PLATFORMS=axon (remote TPU tunnel) and a
sitecustomize that may import jax at interpreter startup. Tests must run
on the local CPU backend (fast, 8 virtual devices for sharding tests), so
we *override* the platform — backends initialize lazily, so doing this
before any jax computation is sufficient even if jax is already imported.

The driver's multichip dry-run uses the same mechanism
(xla_force_host_platform_device_count).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the HH256 device kernel costs ~10 s of
    # XLA compile per distinct shape — cache across test runs
    _cache = os.path.join(os.path.dirname(__file__), os.pardir,
                          ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "native: exercises the C++ library under ASan/UBSan "
        "(make -C native sanitize; run with `pytest -m native`)")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests driven by a seeded NaughtyDisk "
        "schedule; cheap seeded subset runs in tier-1, long randomized "
        "schedules are additionally marked slow. Reproduce any failure "
        "with MINIO_TPU_CHAOS_SEED=<seed printed in the failing test's "
        "captured stdout>")
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from tier-1 "
        "(-m 'not slow'); run with `pytest -m slow`")
