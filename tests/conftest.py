"""Test configuration: force an 8-device virtual CPU mesh.

The environment ships JAX_PLATFORMS=axon (remote TPU tunnel) and a
sitecustomize that may import jax at interpreter startup. Tests must run
on the local CPU backend (fast, 8 virtual devices for sharding tests), so
we *override* the platform — backends initialize lazily, so doing this
before any jax computation is sufficient even if jax is already imported.

The driver's multichip dry-run uses the same mechanism
(xla_force_host_platform_device_count).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the HH256 device kernel costs ~10 s of
    # XLA compile per distinct shape — cache across test runs
    _cache = os.path.join(os.path.dirname(__file__), os.pardir,
                          ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass


import threading
import time

import pytest

# test modules that run with the lock-order watchdog ON by default
# (opt out with MINIO_TPU_LOCKCHECK=off): the suites that actually
# interleave threads, so a future lock-order inversion fails loudly in
# tier-1 instead of hanging a production box
_LOCKCHECK_MODULES = ("test_chaos", "test_concurrency", "test_lockcheck")


@pytest.fixture(autouse=True)
def _lockcheck_watchdog(request):
    mod = request.module.__name__.rpartition(".")[2]
    # honor every false spelling the knob vocabulary accepts
    opted_out = os.environ.get("MINIO_TPU_LOCKCHECK", "").strip().lower() \
        in ("off", "0", "false", "no")
    if mod not in _LOCKCHECK_MODULES or opted_out:
        yield
        return
    from minio_tpu.utils import lockcheck
    prev = os.environ.get("MINIO_TPU_LOCKCHECK")
    os.environ["MINIO_TPU_LOCKCHECK"] = "on"
    lockcheck.refresh()
    lockcheck.reset()
    try:
        yield
        # cycles raised on daemon/background threads are swallowed by
        # their thread loops — surface them here
        cycles = lockcheck.violations("cycle")
        assert not cycles, (
            "lock-order watchdog recorded cycle(s): "
            + "; ".join(v.detail for v in cycles))
    finally:
        if prev is None:
            os.environ.pop("MINIO_TPU_LOCKCHECK", None)
        else:
            os.environ["MINIO_TPU_LOCKCHECK"] = prev
        lockcheck.refresh()
        lockcheck.reset()


# process-global worker pools that are CREATED lazily and live for the
# interpreter's lifetime by design (metadata._POOL drive fan-out,
# pipeline.PREFETCH_POOL) — the leak sentinel must not blame the first
# test that happens to touch them
_LONGLIVED_PREFIXES = ("drive-io", "get-prefetch")


@pytest.fixture(autouse=True)
def _thread_leak_sentinel():
    """No stray non-daemon threads may survive a test: a leaked
    scheduler dispatch pool or cluster worker keeps the interpreter
    alive after pytest finishes and convoys later tests. Fixtures that
    start workers must close() them. Daemon threads are exempt (all
    long-running daemons in-tree are daemonized); so are the
    process-global lazy pools above."""
    before = set(threading.enumerate())
    yield
    def strays():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive() and not t.daemon
                and not t.name.startswith(_LONGLIVED_PREFIXES)]
    s = strays()
    deadline = time.time() + 2.0
    while s and time.time() < deadline:
        for t in s:                 # a finishing worker gets a grace join
            t.join(timeout=0.25)
        s = strays()
    assert not s, (
        "test leaked non-daemon thread(s): "
        + ", ".join(sorted(t.name for t in s))
        + " — the owning fixture must close() its workers")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "native: exercises the C++ library under ASan/UBSan "
        "(make -C native sanitize; run with `pytest -m native`)")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests driven by a seeded NaughtyDisk "
        "schedule; cheap seeded subset runs in tier-1, long randomized "
        "schedules are additionally marked slow. Reproduce any failure "
        "with MINIO_TPU_CHAOS_SEED=<seed printed in the failing test's "
        "captured stdout>")
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from tier-1 "
        "(-m 'not slow'); run with `pytest -m slow`")
