"""Peer control plane + bootstrap verify tests."""

from __future__ import annotations

import pytest

from minio_tpu.distributed.local_locker import LocalLocker
from minio_tpu.distributed.peer_rpc import (BootstrapRPCServer,
                                            NotificationSys,
                                            PeerRPCClient, PeerRPCServer,
                                            system_config_hash,
                                            verify_server_system_config)
from minio_tpu.distributed.transport import RPCServer

AK, SK = "peerak", "peersecret12345"


@pytest.fixture()
def mesh():
    """3 peer nodes with injected hooks."""
    hosts, servers, clients = [], [], []
    reloaded = []
    for i in range(3):
        srv = PeerRPCServer(AK, SK, node_id=f"node{i}")
        srv.get_server_info = lambda i=i: {"drives": 4, "idx": i}
        lk = LocalLocker()
        lk.lock(f"uid{i}", [f"res{i}"], "o")
        srv.get_locks = lk.dump
        srv.reload_bucket_metadata = \
            lambda b, i=i: reloaded.append((i, b))
        host = RPCServer().start()
        host.mount(srv.handler)
        hosts.append(host)
        servers.append(srv)
        clients.append(PeerRPCClient("127.0.0.1", host.port, AK, SK))
    yield servers, clients, reloaded
    for c in clients:
        c.close()
    for h in hosts:
        h.stop()


def test_server_info_broadcast(mesh):
    _, clients, _ = mesh
    ns = NotificationSys(clients)
    infos = ns.server_info_all()
    assert len(infos) == 3
    assert {i["node"] for i in infos} == {"node0", "node1", "node2"}
    assert all(i["drives"] == 4 for i in infos)


def test_top_locks_merges_all_nodes(mesh):
    _, clients, _ = mesh
    ns = NotificationSys(clients)
    locks = ns.top_locks()
    assert set(locks) == {"res0", "res1", "res2"}


def test_reload_bucket_metadata_fanout(mesh):
    _, clients, reloaded = mesh
    ns = NotificationSys(clients)
    oks = ns.reload_bucket_metadata("mybucket")
    assert all(oks)
    assert sorted(reloaded) == [(0, "mybucket"), (1, "mybucket"),
                                (2, "mybucket")]


def test_dead_peer_tolerated(mesh):
    _, clients, _ = mesh
    dead = PeerRPCClient("127.0.0.1", 1, AK, SK, timeout=0.5)
    ns = NotificationSys(clients + [dead])
    infos = ns.server_info_all()
    assert infos[-1] is None
    assert sum(1 for i in infos if i) == 3
    dead.close()


def test_bootstrap_verify_matches():
    eps = ["node0:9000/d1", "node1:9000/d1"]
    host = RPCServer().start()
    host.mount(BootstrapRPCServer(AK, SK, eps).handler)
    verify_server_system_config([("127.0.0.1", host.port)], eps, AK, SK,
                                retries=3, interval=0.1)
    host.stop()


def test_bootstrap_verify_mismatch_raises():
    host = RPCServer().start()
    host.mount(BootstrapRPCServer(AK, SK, ["node0:9000/other"]).handler)
    with pytest.raises(RuntimeError, match="different cluster config"):
        verify_server_system_config(
            [("127.0.0.1", host.port)], ["node0:9000/d1"], AK, SK,
            retries=3, interval=0.1)
    host.stop()


def test_config_hash_stability():
    a = system_config_hash(["b", "a"], "k", "s")
    b = system_config_hash(["a", "b"], "k", "s")
    assert a == b
    assert a != system_config_hash(["a", "b"], "k", "other")
