"""Multi-tenant QoS plane (ISSUE 19): tier-1 pins of the tentpole.

  * tenant resolution — root / IAM user / service account / STS temp
    creds all roll up to the right billing account, from the claimed
    access key (pre-auth) AND the verified credential (post-auth),
    over the wire on BOTH frontends;
  * weighted admission shares — a lone tenant borrows the whole gate,
    equal shares split it, a bought share moves the bound (unit tests
    on QoSPlane.admit_slot, no HTTP);
  * budget refusals answer 503 SlowDown + Retry-After with ZERO body
    bytes read, and land in requests_shed_total{reason=tenant} plus
    the per-tenant kind counter;
  * the budget registry persists to every pool with regfence lineage:
    restart reloads it, a same-epoch fork is a detected + repaired
    fsck finding (registry_epoch_fork), never a silent merge;
  * MINIO_TPU_QOS=off (the default) is byte-identical on the wire and
    touches no QoS counter — even with budgets registered that would
    refuse every request if the plane ran;
  * a noisy tenant flooding through a NaughtyDisk-stalled drive sheds
    at its own share while the polite tenant's requests all land.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import socket
import threading
import time
import urllib.parse

import pytest

from minio_tpu.iam.sys import IAMSys
from minio_tpu.object.fsck import run_fsck
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.qos import (QOS_CONFIG_OBJECT, Budget, QoSConfigError,
                              QoSPlane, QoSRegistry, claimed_access_key)
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.naughty import NaughtyDisk
from minio_tpu.storage.xl_storage import MINIO_META_BUCKET, XLStorage
from minio_tpu.utils import regfence, telemetry
from minio_tpu.utils.bandwidth import TokenBucket

CREDS = Credentials("qosrootkey123", "qosrootsecret123")
ALICE = Credentials("alicetenant12", "alicesecret1234")
BOB = Credentials("bobtenant1234", "bobsecret123456")
REGION = "us-east-1"
BLOCK = 1 << 16


@pytest.fixture(scope="module")
def layer(tmp_path_factory):
    root = tmp_path_factory.mktemp("qosdrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(6)], 1, 6, 2,
        block_size=BLOCK)
    yield sets
    sets.close()


def _mk_iam() -> IAMSys:
    iam = IAMSys(root_cred=CREDS)        # in-memory store
    iam.add_user(ALICE.access_key, ALICE.secret_key)
    iam.add_user(BOB.access_key, BOB.secret_key)
    iam.attach_policy("readwrite", user=ALICE.access_key)
    iam.attach_policy("readwrite", user=BOB.access_key)
    return iam


def _mk_server(layer, iam, **env) -> S3Server:
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return S3Server(layer, creds=CREDS, region=REGION,
                        iam=iam).start()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture(params=["edge", "threaded"])
def any_server(request, layer, monkeypatch):
    # enabled() reads the knob per request, so it must stay set for
    # the whole test, not just through server construction
    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    srv = _mk_server(
        layer, _mk_iam(),
        MINIO_TPU_EDGE="on" if request.param == "edge" else "off")
    assert srv.edge_enabled == (request.param == "edge")
    yield srv
    srv.stop()


def _signed_headers(cred, method, path, port,
                    payload_hash, extra=None) -> dict:
    hdrs = {"host": f"127.0.0.1:{port}"}
    hdrs.update(extra or {})
    return sig.sign_v4(method, urllib.parse.quote(path), {}, hdrs,
                       payload_hash, cred, REGION)


def _request(port, cred, method, path, body=b""):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    hdrs = _signed_headers(cred, method, path, port,
                           hashlib.sha256(body).hexdigest())
    conn.request(method, urllib.parse.quote(path), body=body,
                 headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    headers = {k.lower(): v for k, v in resp.getheaders()}
    conn.close()
    return resp.status, headers, data


def _read_http_response(sock: socket.socket):
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            break
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for line in lines[1:]:
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    want = int(headers.get("content-length", 0))
    while len(rest) < want:
        chunk = sock.recv(65536)
        if not chunk:
            break
        rest += chunk
    return status, headers, rest[:want]


def _claim_hdrs(access_key: str) -> dict:
    """Headers carrying only the CLAIM of an access key (no valid
    signature) — all the tenant mapper reads pre-auth."""
    return {"authorization":
            f"AWS4-HMAC-SHA256 Credential={access_key}/20260807/"
            f"{REGION}/s3/aws4_request, SignedHeaders=host, "
            "Signature=0"}


def _counter(name: str):
    return telemetry.REGISTRY.counter(name)


def _reqs(tenant: str) -> float:
    return _counter("minio_tpu_qos_tenant_requests_total").value(
        tenant=tenant)


def _shed_reason(reason: str = "tenant") -> float:
    return _counter("minio_tpu_requests_shed_total").value(
        reason=reason)


def _shed_kind(tenant: str, kind: str) -> float:
    return _counter("minio_tpu_qos_tenant_shed_total").value(
        tenant=tenant, kind=kind)


# ---------------------------------------------------------------------------
# tenant resolution
# ---------------------------------------------------------------------------

def test_claimed_access_key_parses_every_auth_flavor():
    ak = "AKIAEXAMPLE12345"
    v4 = {"authorization":
          f"AWS4-HMAC-SHA256 Credential={ak}/20260807/us-east-1/s3/"
          "aws4_request, SignedHeaders=host, Signature=beef"}
    assert claimed_access_key(v4, {}) == ak
    v2 = {"authorization": f"AWS {ak}:c2lnbmF0dXJl"}
    assert claimed_access_key(v2, {}) == ak
    presigned_v4 = {"X-Amz-Credential":
                    [f"{ak}/20260807/us-east-1/s3/aws4_request"]}
    assert claimed_access_key({}, presigned_v4) == ak
    presigned_v2 = {"AWSAccessKeyId": [ak]}
    assert claimed_access_key({}, presigned_v2) == ak
    assert claimed_access_key({}, {}) == ""                # anonymous
    assert claimed_access_key({"authorization": "AWS4-"}, {}) == ""


def test_tenant_resolution_rolls_up_to_parent():
    iam = _mk_iam()
    svc = iam.new_service_account(ALICE.access_key, "svcacctalice1",
                                  "svcsecret123456")
    sts = iam.assume_role(ALICE)
    plane = QoSPlane(QoSRegistry(), iam_lookup=lambda: iam,
                     root_access_key=CREDS.access_key)
    assert plane.resolve_tenant(CREDS.access_key) == "root"
    assert plane.resolve_tenant(ALICE.access_key) == ALICE.access_key
    assert plane.resolve_tenant(svc.access_key) == ALICE.access_key
    assert plane.resolve_tenant(sts.access_key) == ALICE.access_key
    assert plane.resolve_tenant("") == "anonymous"
    assert plane.resolve_tenant("neverregistered") == "unknown"
    # the post-auth verified-credential path lands on the same tenant
    assert plane.tenant_for_cred(CREDS) == "root"
    assert plane.tenant_for_cred(svc) == ALICE.access_key
    assert plane.tenant_for_cred(sts) == ALICE.access_key
    assert plane.tenant_for_cred(None) == "anonymous"


def test_auth_matrix_on_the_wire(any_server):
    """Root, a plain IAM user, her service account, and her STS temp
    creds each land their request on the RIGHT tenant counter — on
    both frontends (the fixture params them)."""
    iam = any_server.api.iam
    svc = iam.new_service_account(ALICE.access_key, "svcacctalice1",
                                  "svcsecret123456")
    sts = iam.assume_role(ALICE)
    port = any_server.port
    bucket = f"qosm-{port}"
    assert _request(port, CREDS, "PUT", f"/{bucket}")[0] == 200
    body = b"qos auth matrix payload " * 8
    assert _request(port, CREDS, "PUT", f"/{bucket}/obj",
                    body)[0] == 200
    for cred, tenant in ((CREDS, "root"),
                         (ALICE, ALICE.access_key),
                         (svc, ALICE.access_key),
                         (sts, ALICE.access_key)):
        before = _reqs(tenant)
        st, _hdrs, data = _request(port, cred, "GET",
                                   f"/{bucket}/obj")
        assert st == 200 and data == body, cred.access_key
        assert _reqs(tenant) == before + 1, cred.access_key


# ---------------------------------------------------------------------------
# token-bucket probes (the admission-side TokenBucket extension)
# ---------------------------------------------------------------------------

def test_token_bucket_probes_charge_and_peek():
    tb = TokenBucket(10.0)              # 10 tokens/s, burst 10
    assert tb.try_take(4) == 0.0        # charged
    assert tb.peek(6) == 0.0            # affordable, NOT charged
    assert tb.try_take(6) == 0.0        # the peeked tokens still there
    assert tb.try_take(5) > 0.0         # empty: refused, uncharged
    assert tb.peek(5) > 0.0             # still refused — peek never took
    unlimited = TokenBucket(0.0)        # zero rate = no budget
    assert unlimited.try_take(1 << 30) == 0.0
    assert unlimited.peek(1 << 30) == 0.0


# ---------------------------------------------------------------------------
# weighted admission shares
# ---------------------------------------------------------------------------

def _admitting_plane(iam) -> QoSPlane:
    return QoSPlane(QoSRegistry(), iam_lookup=lambda: iam,
                    root_access_key=CREDS.access_key)


def test_lone_tenant_borrows_the_whole_gate(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    plane = _admitting_plane(_mk_iam())
    cap, a = 4, ALICE.access_key

    def admit(ak):
        return plane.admit_slot("GET", "/b/o", {}, _claim_hdrs(ak),
                                cap)

    for _ in range(cap):                # only tenant active: full gate
        assert admit(a) == a
    got = admit(a)                      # slot cap+1 refuses
    assert not isinstance(got, str)
    assert got.kind == "share" and got.retry_after >= 1
    plane.release(a)
    assert admit(a) == a                # a released slot re-admits


def test_equal_shares_split_the_gate(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    plane = _admitting_plane(_mk_iam())
    cap = 4
    a, b = ALICE.access_key, BOB.access_key

    def admit(ak):
        return plane.admit_slot("GET", "/b/o", {}, _claim_hdrs(ak),
                                cap)

    assert admit(a) == a                # both tenants now active
    assert admit(b) == b
    assert admit(a) == a                # alice reaches her half (2/4)
    got = admit(a)
    assert not isinstance(got, str) and got.kind == "share"
    assert admit(b) == b                # bob's own half is untouched
    got = admit(b)
    assert not isinstance(got, str)     # ... until he reaches it too


def test_bought_share_moves_the_bound(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    iam = _mk_iam()
    plane = _admitting_plane(iam)
    cap = 4
    a, b = ALICE.access_key, BOB.access_key
    plane.registry.set_budget("tenant", Budget(a, share=3.0))

    def admit(ak):
        return plane.admit_slot("GET", "/b/o", {}, _claim_hdrs(ak),
                                cap)

    assert admit(b) == b                # bob active on the default 1.0
    for _ in range(3):                  # alice's 3-of-4 guarantee
        assert admit(a) == a
    got = admit(a)
    assert not isinstance(got, str) and got.kind == "share"
    got = admit(b)                      # bob is at his 1-of-4 already
    assert not isinstance(got, str) and got.kind == "share"


# ---------------------------------------------------------------------------
# budget refusal: 503 before any body byte
# ---------------------------------------------------------------------------

def test_budget_refusal_reads_zero_body_bytes(any_server):
    """A drained request-rate budget refuses a PUT announcing a 1 MiB
    body to which NO body byte is ever sent — a server that waited for
    the body before deciding would hang here. Both frontends answer
    503 SlowDown + Retry-After + close, the shed lands in
    requests_shed_total{reason=tenant} AND the per-tenant kind
    counter."""
    port = any_server.port
    qos = any_server.api.qos
    qos.registry.set_budget("tenant", Budget(BOB.access_key, rps=0.001))
    # one cheap request drains bob's single burst token
    assert _request(port, BOB, "GET", "/")[0] == 200
    before_global = _shed_reason("tenant")
    before_kind = _shed_kind(BOB.access_key, "rate")
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=30) as s:
        auth = _claim_hdrs(BOB.access_key)["authorization"]
        head = (f"PUT /shedq-{port}/obj HTTP/1.1\r\n"
                f"Host: 127.0.0.1:{port}\r\n"
                f"Authorization: {auth}\r\n"
                f"Content-Length: {1 << 20}\r\n\r\n").encode()
        s.sendall(head)                 # zero body bytes follow
        st, headers, body = _read_http_response(s)
        assert st == 503 and b"SlowDown" in body
        assert headers.get("connection") == "close"
        assert int(headers.get("retry-after", 0)) >= 1
        assert s.recv(16) == b""        # server closed the socket
    assert _shed_reason("tenant") == before_global + 1
    assert _shed_kind(BOB.access_key, "rate") == before_kind + 1


def test_byte_budget_refuses_oversized_put_pre_body(any_server):
    """An rx byte budget whose bucket cannot cover the announced
    Content-Length refuses pre-body (kind=bytes): the 400-byte PUT
    sheds while a 40-byte PUT (within burst) still lands."""
    port = any_server.port
    qos = any_server.api.qos
    qos.registry.set_budget("tenant",
                            Budget(ALICE.access_key, rx_bps=100.0))
    bucket = f"qosb-{port}"
    assert _request(port, CREDS, "PUT", f"/{bucket}")[0] == 200
    before_kind = _shed_kind(ALICE.access_key, "bytes")
    st, _h, _d = _request(port, ALICE, "PUT", f"/{bucket}/small",
                          b"x" * 40)
    assert st == 200
    st, _h, data = _request(port, ALICE, "PUT", f"/{bucket}/big",
                            b"y" * 400)
    assert st == 503 and b"SlowDown" in data
    assert _shed_kind(ALICE.access_key, "bytes") == before_kind + 1


# ---------------------------------------------------------------------------
# registry: persistence, restart, fork
# ---------------------------------------------------------------------------

def _zones(tmp_path, pools=2):
    return ErasureServerSets(
        [ErasureSets.from_drives(
            [str(tmp_path / f"p{p}d{j}") for j in range(4)], 1, 4, 2,
            block_size=BLOCK, enable_mrf=False)
         for p in range(pools)],
        load_topology=False)


def test_registry_persists_and_reloads_across_restart(tmp_path):
    zz = _zones(tmp_path)
    try:
        reg = QoSRegistry(zz)
        reg.set_budget("tenant", Budget("alice", share=2.0, rps=5.0))
        reg.set_budget("tier", Budget("WARM", rps=1.0,
                                      tx_bps=float(1 << 20)))
        assert reg.epoch == 2
        fresh = QoSRegistry(zz)          # the restart
        assert fresh.load()
        assert fresh.epoch == 2
        assert fresh.lineage == reg.lineage
        assert fresh.get("tenant", "alice").share == 2.0
        assert fresh.get("tier", "WARM").tx_bps == float(1 << 20)
        reg.remove_budget("tenant", "alice")
        fresh2 = QoSRegistry(zz)
        assert fresh2.load()
        assert fresh2.epoch == 3
        assert fresh2.get("tenant", "alice") is None
        with pytest.raises(QoSConfigError):
            reg.set_budget("nope", Budget("x"))
        with pytest.raises(QoSConfigError):
            reg.remove_budget("tenant", "neverwas")
        with pytest.raises(QoSConfigError):
            Budget.from_dict({"name": "n", "rps": -1})
    finally:
        zz.close()


def _qos_fork_doc(epoch: int, writer: str) -> dict:
    return {"epoch": epoch, "updated": time.time(),
            "tenants": [{"name": "alice", "share": 2.0, "rps": 0.0,
                         "rx_bps": 0.0, "tx_bps": 0.0}],
            "tiers": [], "writer": writer, "parent_lineage": "",
            "lineage": regfence.lineage("", epoch, writer)}


def test_registry_fork_detected_and_repaired_by_fsck(tmp_path):
    zz = _zones(tmp_path)
    try:
        raw_a = json.dumps(_qos_fork_doc(7, "nodeA")).encode()
        raw_b = json.dumps(_qos_fork_doc(7, "nodeB")).encode()
        zz.server_sets[0].put_object(MINIO_META_BUCKET,
                                     QOS_CONFIG_OBJECT, raw_a)
        zz.server_sets[1].put_object(MINIO_META_BUCKET,
                                     QOS_CONFIG_OBJECT, raw_b)
        # load never coin-flips: deterministic winner is nodeB
        reg = QoSRegistry(zz)
        assert reg.load()
        assert (reg.epoch, reg.writer) == (7, "nodeB")
        # the fork is a detected finding, not a silent merge
        rep = run_fsck(zz, tmp_age_s=0)
        forks = [f for f in rep.findings
                 if f.cls == "registry_epoch_fork"
                 and f.object == QOS_CONFIG_OBJECT]
        assert len(forks) == 1
        assert "nodeB" in forks[0].detail
        # repair archives the loser and converges every pool
        rep = run_fsck(zz, repair=True, tmp_age_s=0)
        assert rep.repaired_counts().get("registry_epoch_fork") == 1
        from minio_tpu.object.fsck import _get_pool_bytes
        for pool in zz.server_sets:
            assert _get_pool_bytes(pool, QOS_CONFIG_OBJECT) == raw_b
        rep = run_fsck(zz, tmp_age_s=0)
        assert not [f for f in rep.findings
                    if f.cls == "registry_epoch_fork"]
    finally:
        zz.close()


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------

def test_admin_qos_roundtrip(layer, monkeypatch):
    """The admin endpoint + madmin SDK drive the registry end to end:
    set bumps the epoch and shows up in get (and in live stats),
    remove drops it, a bad budget answers AdminInvalidArgument, and
    the change journals qos.update."""
    from minio_tpu.madmin import AdminClient, AdminClientError
    from minio_tpu.s3.admin import mount_admin
    from minio_tpu.utils import eventlog

    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    srv = _mk_server(layer, _mk_iam())
    mount_admin(srv)
    try:
        adm = AdminClient("127.0.0.1", srv.port, CREDS.access_key,
                          CREDS.secret_key, region=REGION)
        base = adm.qos_get()
        assert base["enabled"] is True
        out = adm.qos_set("alice", share=2.0, rps=50.0)
        assert out["epoch"] == base["epoch"] + 1
        out = adm.qos_set("WARM", scope="tier", tx_bps=float(1 << 20))
        got = adm.qos_get()
        assert got["epoch"] == base["epoch"] + 2
        assert {b["name"] for b in got["tenants"]} >= {"alice"}
        assert {b["name"] for b in got["tiers"]} >= {"WARM"}
        alice = [b for b in got["tenants"] if b["name"] == "alice"][0]
        assert alice["share"] == 2.0 and alice["rps"] == 50.0
        assert "alice" in got["stats"]       # budget names ride stats
        events = [e for e in eventlog.JOURNAL.recent(50)
                  if e["class"] == "qos.update"]
        assert events and events[-1]["attrs"]["epoch"] == got["epoch"]
        adm.qos_remove("alice")
        adm.qos_remove("WARM", scope="tier")
        got = adm.qos_get()
        assert not [b for b in got["tenants"] if b["name"] == "alice"]
        with pytest.raises(AdminClientError):
            adm.qos_set("bad", rps=-1.0)
        with pytest.raises(AdminClientError):
            adm.qos_remove("neverwas")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# default-off parity
# ---------------------------------------------------------------------------

_VOLATILE_HEADERS = ("date", "last-modified", "x-amz-request-id")


def _normalized(status, headers, data):
    kept = {k: v for k, v in headers.items()
            if k not in _VOLATILE_HEADERS}
    return status, sorted(kept.items()), data


@pytest.mark.parametrize("edge", ["on", "off"])
def test_default_off_is_byte_identical(layer, edge, monkeypatch):
    """With MINIO_TPU_QOS unset (the default) the wire behavior is
    identical to a QoS-on server with no budgets — and budgets that
    WOULD refuse every alice request are completely inert: all 200,
    no QoS counter moves, nothing sheds."""
    srv_off = _mk_server(layer, _mk_iam(), MINIO_TPU_EDGE=edge)
    srv_on = _mk_server(layer, _mk_iam(), MINIO_TPU_EDGE=edge)
    try:
        # poison the off server's registry: rps AND rx budgets that
        # would shed everything alice does if the plane consulted them
        srv_off.api.qos.registry.set_budget(
            "tenant", Budget(ALICE.access_key, rps=0.001, rx_bps=1.0))
        before_reqs = _reqs(ALICE.access_key)
        before_shed = _shed_reason("tenant")
        bucket = "qpar"
        body = b"parity payload " * 16
        wire = []
        # the knob is process-global and read per request, so each
        # server's phase runs under its own setting
        for srv, qos in ((srv_off, ""), (srv_on, "on")):
            if qos:
                monkeypatch.setenv("MINIO_TPU_QOS", qos)
            else:
                monkeypatch.delenv("MINIO_TPU_QOS", raising=False)
            assert _request(srv.port, CREDS, "PUT",
                            f"/{bucket}-{srv.port}")[0] == 200
            for _ in range(3):          # would drain rps=0.001 thrice
                st, hdrs, data = _request(
                    srv.port, ALICE, "PUT",
                    f"/{bucket}-{srv.port}/obj", body)
                assert st == 200
            wire.append([
                _normalized(*_request(srv.port, ALICE, "PUT",
                                      f"/{bucket}-{srv.port}/obj",
                                      body)),
                _normalized(*_request(srv.port, ALICE, "GET",
                                      f"/{bucket}-{srv.port}/obj")),
            ])
        assert wire[0] == wire[1]       # off tree == on tree, no budgets
        # the off server never consulted the plane: alice's counters
        # moved ONLY for the on-server requests (5 of the 10)
        assert _reqs(ALICE.access_key) == before_reqs + 5
        assert _shed_reason("tenant") == before_shed
    finally:
        srv_off.stop()
        srv_on.stop()


# ---------------------------------------------------------------------------
# noisy-neighbor isolation under a gray drive
# ---------------------------------------------------------------------------

def test_noisy_tenant_sheds_polite_tenant_lands(tmp_path, monkeypatch):
    """Capacity 2, equal shares, a NaughtyDisk stalling every write
    verb: bob floods on two connections, alice PUTs sequentially.
    The share rule bounds bob to one in-flight slot, so alice's
    requests all land (never refused for the share), while bob's
    surplus stream sheds under reason=tenant."""
    drives: list = [XLStorage(str(tmp_path / f"d{j}"))
                    for j in range(4)]
    nd = NaughtyDisk(drives[0], enabled=False)
    drives[0] = nd
    sets = ErasureSets.from_storage(drives, set_count=1,
                                    set_drive_count=4, parity=2,
                                    block_size=BLOCK)
    monkeypatch.setenv("MINIO_TPU_QOS", "on")
    srv = _mk_server(sets, _mk_iam(), MINIO_TPU_EDGE="on")
    try:
        srv.api.set_max_clients(2)
        srv.api.qos.registry.set_budget(
            "tenant", Budget(ALICE.access_key, share=1.0))
        srv.api.qos.registry.set_budget(
            "tenant", Budget(BOB.access_key, share=1.0))
        assert _request(srv.port, CREDS, "PUT", "/nqos")[0] == 200
        nd.stall_verbs = {v: 0.05 for v in
                          ("append_file", "create_file", "write_all",
                           "write_metadata", "rename_data",
                           "rename_file")}
        nd.arm()
        before_alice = _shed_kind(ALICE.access_key, "share")
        before_global = _shed_reason("tenant")
        body = b"n" * (8 << 10)
        stop = threading.Event()

        def flood(w: int) -> None:
            i = 0
            while not stop.is_set():
                try:
                    _request(srv.port, BOB, "PUT",
                             f"/nqos/b-{w}-{i}", body)
                except OSError:
                    pass                # refused mid-send: still a shed
                i += 1

        threads = [threading.Thread(target=flood, args=(w,),
                                    daemon=True) for w in range(2)]
        for t in threads:
            t.start()
        try:
            for i in range(4):          # polite alice, one at a time
                while True:
                    st, _h, _d = _request(srv.port, ALICE, "PUT",
                                          f"/nqos/a-{i}", body)
                    if st == 200:
                        break
                    assert st == 503, st
                    time.sleep(0.01)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        nd.stall_verbs = {}
        # bob's surplus stream shed at HIS budget...
        assert _shed_kind(BOB.access_key, "share") > 0
        assert _shed_reason("tenant") > before_global
        # ...while alice was never refused for hers
        assert _shed_kind(ALICE.access_key, "share") == before_alice
    finally:
        srv.stop()
        sets.close()
