"""Tiering-plane tests: tier registry persistence, the transition
worker (remote move + zero-data stub + local shard reclaim), the
InvalidObjectState read gate, RestoreObject round trips (etag/version
fidelity), restore-expiry reclaim, noncurrent transitions, and the
admin/S3 HTTP surface — including the end-to-end acceptance flow
PUT → crawler transition → InvalidObjectState → restore → identical
bytes → expiry reclaim."""

from __future__ import annotations

import io
import json
import os
import time

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.background import DataUsageCrawler
from minio_tpu.object.engine import GetOptions, PutOptions
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.storage import datatypes as dt
from minio_tpu.tier.client import (FSTierClient, TierClientError,
                                   TierObjectNotFound)
from minio_tpu.tier.config import TierConfig, TierConfigError, TierManager
from minio_tpu.tier.transition import (TransitionWorker, free_remote,
                                       noncurrent_transition_action,
                                       restore_object,
                                       restore_reclaim_action,
                                       transition_action)

BLOCK = 1 << 16
DAY = 86400
NEVER_BUSY = dict(busy_fn=lambda: False)

LC_TRANSITION = """<LifecycleConfiguration>
  <Rule><ID>t</ID><Status>Enabled</Status><Prefix></Prefix>
    <Transition><Days>1</Days><StorageClass>cold</StorageClass>
    </Transition></Rule>
</LifecycleConfiguration>"""


def make_sets(tmp_path, tag: str = "p0", drives: int = 4,
              **kw) -> ErasureSets:
    return ErasureSets.from_drives(
        [str(tmp_path / f"{tag}d{i}") for i in range(drives)], 1,
        drives, 2, block_size=BLOCK, **kw)


class FakeBucketMeta:
    """bucket_meta_sys stub: one lifecycle XML for every bucket."""

    def __init__(self, lifecycle_xml: str = "", versioned: bool = False):
        self.lifecycle_xml = lifecycle_xml
        self._versioned = versioned

    def get(self, bucket):
        return self

    def versioning_enabled(self) -> bool:
        return self._versioned


@pytest.fixture()
def env(tmp_path):
    sets = make_sets(tmp_path, enable_mrf=False)
    zz = ErasureServerSets([sets])
    zz.make_bucket("b")
    tiers = TierManager(zz)
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    worker = TransitionWorker(zz, tiers, **NEVER_BUSY).start()
    yield zz, tiers, worker, tmp_path
    worker.close()
    zz.close()


# ---------------------------------------------------------------------------
# tier clients
# ---------------------------------------------------------------------------

def test_fs_client_round_trip(tmp_path):
    c = FSTierClient(str(tmp_path / "t"))
    etag = c.put("b/o/v1/abc", io.BytesIO(b"x" * 1000), 1000)
    assert etag
    assert c.head("b/o/v1/abc") == 1000
    assert b"".join(c.get("b/o/v1/abc")) == b"x" * 1000
    assert b"".join(c.get("b/o/v1/abc", offset=10, length=5)) == b"xxxxx"
    c.delete("b/o/v1/abc")
    with pytest.raises(TierObjectNotFound):
        c.head("b/o/v1/abc")
    c.delete("b/o/v1/abc")          # idempotent


def test_fs_client_refuses_short_write(tmp_path):
    c = FSTierClient(str(tmp_path / "t"))
    with pytest.raises(TierClientError):
        c.put("k", io.BytesIO(b"short"), 1000)
    # the staged tmp never became the object
    with pytest.raises(TierObjectNotFound):
        c.head("k")


def test_fs_client_rejects_escaping_keys(tmp_path):
    c = FSTierClient(str(tmp_path / "t"))
    with pytest.raises(TierClientError):
        c.put("../../etc/shadow", io.BytesIO(b"x"), 1)


# ---------------------------------------------------------------------------
# tier registry persistence
# ---------------------------------------------------------------------------

def test_tier_config_persists_across_pools_highest_epoch(tmp_path):
    zz = ErasureServerSets([make_sets(tmp_path, "p0", enable_mrf=False),
                            make_sets(tmp_path, "p1", enable_mrf=False)])
    try:
        tiers = TierManager(zz)
        tiers.add(TierConfig("cold", "fs",
                             {"path": str(tmp_path / "t1")}))
        tiers.add(TierConfig("ice", "fs",
                             {"path": str(tmp_path / "t2")}))
        assert tiers.epoch == 2

        # a fresh manager over the same pools recovers the registry
        t2 = TierManager(zz)
        assert t2.load()
        assert t2.epoch == 2
        assert {t["name"] for t in t2.list()} == {"cold", "ice"}

        # highest epoch wins when one pool holds a stale doc
        from minio_tpu.storage.xl_storage import MINIO_META_BUCKET
        from minio_tpu.tier.config import TIER_CONFIG_OBJECT
        stale = {"epoch": 1, "tiers": [{"name": "old", "type": "fs",
                                        "params": {"path": "/x"}}]}
        zz.server_sets[1].put_object(MINIO_META_BUCKET,
                                     TIER_CONFIG_OBJECT,
                                     json.dumps(stale).encode())
        t3 = TierManager(zz)
        assert t3.load()
        assert t3.epoch == 2 and "cold" in t3.tiers
    finally:
        zz.close()


def test_tier_registry_crud_rules(env):
    zz, tiers, _, tmp_path = env
    with pytest.raises(TierConfigError):
        tiers.add(TierConfig("cold", "fs",
                             {"path": str(tmp_path / "dup")}))
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "dup")}),
              update=True)
    with pytest.raises(api_errors.TierNotFound):
        tiers.remove("nope")
    with pytest.raises(TierConfigError):
        tiers.add(TierConfig("bad", "fs", {}))        # fs needs path
    with pytest.raises(TierConfigError):
        tiers.add(TierConfig("bad", "wat", {}))       # unknown type
    # secrets are redacted in listings
    tiers.add(TierConfig("remote", "s3",
                         {"host": "h", "bucket": "b",
                          "access_key": "AK", "secret_key": "SECRET"}))
    listed = {t["name"]: t for t in tiers.list()}
    assert listed["remote"]["params"]["secret_key"] == "REDACTED"
    assert listed["remote"]["params"]["access_key"] == "AK"


# ---------------------------------------------------------------------------
# the end-to-end acceptance flow (engine level)
# ---------------------------------------------------------------------------

def test_e2e_transition_restore_reclaim(env):
    """PUT → crawler transitions per lifecycle rule → GET returns
    InvalidObjectState → RestoreObject → GET serves bytes identical to
    the original (etag/version id preserved) → restore expiry reclaims
    the local copy."""
    zz, tiers, worker, tmp_path = env
    payload = os.urandom(200_000)
    info = zz.put_object("b", "obj", payload,
                         opts=PutOptions(versioned=True))
    orig_vid, orig_etag, orig_mt = info.version_id, info.etag, \
        info.mod_time

    # drive the REAL crawler action path, clock warped 2 days ahead so
    # the Days=1 Transition rule is due
    later = time.time() + 2 * DAY
    crawler = DataUsageCrawler(
        zz, persist=False,
        actions=[transition_action(FakeBucketMeta(LC_TRANSITION),
                                   worker, now_fn=lambda: later)])
    crawler.scan_once()
    assert worker.drain(30), worker.stats()
    assert worker.stats()["moved"] == 1

    # GET gates, HEAD still serves the stub's metadata
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "obj")
    oi = zz.get_object_info("b", "obj")
    assert oi.size == len(payload)
    md = oi.user_defined
    assert md[dt.TRANSITION_STATUS_KEY] == dt.TRANSITION_COMPLETE
    assert md[dt.TRANSITION_TIER_KEY] == "cold"
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    assert tiers.client("cold").head(remote_key) == len(payload)

    # local shards actually reclaimed: no data dirs remain under b/obj
    for i in range(4):
        objdir = tmp_path / f"p0d{i}" / "b" / "obj"
        if objdir.exists():
            assert sorted(p.name for p in objdir.iterdir()) \
                == ["xl.meta"], list(objdir.iterdir())

    # restore: identical bytes, same version id + etag + mod time
    out = restore_object(zz, tiers, "b", "obj", days=1)
    assert out["status"] == "restored"
    oi2, stream = zz.get_object("b", "obj")
    assert b"".join(stream) == payload
    assert oi2.version_id == orig_vid
    assert oi2.etag == orig_etag
    assert oi2.mod_time == pytest.approx(orig_mt, abs=1e-6)
    assert dt.is_restored(oi2.user_defined)

    # a second restore only extends the window (no re-pull)
    out2 = restore_object(zz, tiers, "b", "obj", days=7)
    assert out2["status"] == "updated"
    assert out2["expiry"] > out["expiry"]

    # restore expiry reclaims the local copy: back to the stub
    reclaim = restore_reclaim_action(zz, tiers,
                                     now_fn=lambda: time.time() + 30 * DAY)
    crawler2 = DataUsageCrawler(zz, persist=False, actions=[reclaim])
    crawler2.scan_once()
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "obj")
    # remote copy untouched; version id still intact
    assert tiers.client("cold").head(remote_key) == len(payload)
    assert zz.get_object_info("b", "obj").version_id == orig_vid


def test_transition_skips_overwritten_object(env):
    zz, tiers, _, _ = env
    # a NOT-yet-started worker: the enqueue-time etag is guaranteed to
    # predate the overwrite when the drain finally runs
    frozen = TransitionWorker(zz, tiers, **NEVER_BUSY)
    info = zz.put_object("b", "o", b"old" * 1000)
    frozen.enqueue("b", "o", "", "cold", etag=info.etag)
    zz.put_object("b", "o", b"new" * 2000)   # overwrite before the move
    frozen.start()
    assert frozen.drain(30)
    assert frozen.stats()["skipped"] == 1
    frozen.close()
    _, stream = zz.get_object("b", "o")
    assert b"".join(stream) == b"new" * 2000


def test_transition_worker_dedups_and_bounds(env):
    zz, tiers, worker, _ = env
    worker.close()                  # frozen: entries stay queued
    small = TransitionWorker(zz, tiers, maxsize=2, **NEVER_BUSY)
    assert small.enqueue("b", "x", "", "cold")
    assert not small.enqueue("b", "x", "", "cold")     # dedup
    assert small.enqueue("b", "y", "", "cold")
    assert not small.enqueue("b", "z", "", "cold")     # over maxsize
    assert small.stats()["dropped"] == 1
    small.close()


def test_delete_frees_remote_copy(env):
    zz, tiers, worker, _ = env
    payload = b"c" * 50_000
    info = zz.put_object("b", "gone", payload)
    worker.enqueue("b", "gone", "", "cold", etag=info.etag)
    assert worker.drain(30)
    md = zz.get_object_info("b", "gone").user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    client = tiers.client("cold")
    assert client.head(remote_key) == len(payload)
    zz.delete_object("b", "gone")
    assert free_remote(tiers, md)
    with pytest.raises(TierObjectNotFound):
        client.head(remote_key)


def test_restore_requires_transitioned(env):
    zz, tiers, _, _ = env
    zz.put_object("b", "hot", b"h" * 100)
    with pytest.raises(api_errors.InvalidObjectState):
        restore_object(zz, tiers, "b", "hot")
    with pytest.raises(api_errors.InvalidObjectState):
        restore_object(zz, tiers, "b", "hot", days=0)


def test_noncurrent_transition_action(env):
    zz, tiers, worker, _ = env
    old = zz.put_object("b", "v", b"old" * 500,
                        opts=PutOptions(versioned=True))
    time.sleep(0.01)
    cur = zz.put_object("b", "v", b"new" * 500,
                        opts=PutOptions(versioned=True))
    lc = """<LifecycleConfiguration><Rule>
      <Status>Enabled</Status><Prefix></Prefix>
      <NoncurrentVersionTransition><NoncurrentDays>1</NoncurrentDays>
        <StorageClass>cold</StorageClass></NoncurrentVersionTransition>
    </Rule></LifecycleConfiguration>"""
    act = noncurrent_transition_action(
        FakeBucketMeta(lc), worker, now_fn=lambda: time.time() + 2 * DAY)
    act("b")
    assert worker.drain(30), worker.stats()
    assert worker.stats()["moved"] == 1
    # the CURRENT version still reads; the noncurrent one is a stub
    _, stream = zz.get_object("b", "v")
    assert b"".join(stream) == b"new" * 500
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "v", opts=GetOptions(version_id=old.version_id))
    # and restores by version id
    restore_object(zz, tiers, "b", "v", version_id=old.version_id)
    _, stream = zz.get_object("b", "v",
                              opts=GetOptions(version_id=old.version_id))
    assert b"".join(stream) == b"old" * 500
    assert cur.version_id != old.version_id


def test_multipart_object_transitions_whole(env):
    """A multipart object's parts all live under one data dir: the
    stub rewrite reclaims every part and restore brings the full
    concatenation back."""
    zz, tiers, worker, _ = env
    from minio_tpu.object.multipart import CompletePart
    part = os.urandom(5 << 20)
    uid = zz.new_multipart_upload("b", "mp")
    etags = [zz.put_object_part("b", "mp", uid, n, part, len(part)).etag
             for n in (1, 2)]
    zz.complete_multipart_upload(
        "b", "mp", uid, [CompletePart(i + 1, e)
                         for i, e in enumerate(etags)])
    info = zz.get_object_info("b", "mp")
    worker.enqueue("b", "mp", "", "cold", etag=info.etag)
    assert worker.drain(60)
    assert worker.stats()["moved"] == 1
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "mp")
    restore_object(zz, tiers, "b", "mp")
    _, stream = zz.get_object("b", "mp")
    assert b"".join(stream) == part + part


# ---------------------------------------------------------------------------
# expiry interplay
# ---------------------------------------------------------------------------

def test_expiry_wins_over_transition(env):
    zz, tiers, worker, _ = env
    lc = """<LifecycleConfiguration><Rule>
      <Status>Enabled</Status><Prefix></Prefix>
      <Expiration><Days>1</Days></Expiration>
      <Transition><Days>1</Days><StorageClass>cold</StorageClass>
      </Transition></Rule></LifecycleConfiguration>"""
    zz.put_object("b", "both", b"x" * 1000)
    later = time.time() + 2 * DAY
    act = transition_action(FakeBucketMeta(lc), worker,
                            now_fn=lambda: later)
    act("b", zz.get_object_info("b", "both"))
    assert worker.pending() == 0        # expiry takes precedence


def test_expired_transitioned_object_frees_remote(env):
    """Lifecycle expiry of an (unversioned) transitioned object deletes
    the remote copy too (crawler_action's tier hook)."""
    from minio_tpu.features.lifecycle import crawler_action
    zz, tiers, worker, _ = env
    info = zz.put_object("b", "exp", b"e" * 10_000)
    worker.enqueue("b", "exp", "", "cold", etag=info.etag)
    assert worker.drain(30)
    md = zz.get_object_info("b", "exp").user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    lc = """<LifecycleConfiguration><Rule>
      <Status>Enabled</Status><Prefix></Prefix>
      <Expiration><Days>1</Days></Expiration>
    </Rule></LifecycleConfiguration>"""
    act = crawler_action(FakeBucketMeta(lc), zz,
                         now_fn=lambda: time.time() + 2 * DAY,
                         tiers=tiers)
    act("b", zz.get_object_info("b", "exp"))
    with pytest.raises(api_errors.ObjectNotFound):
        zz.get_object_info("b", "exp")
    with pytest.raises(TierObjectNotFound):
        tiers.client("cold").head(remote_key)


# ---------------------------------------------------------------------------
# HTTP surface: admin tier CRUD + RestoreObject + headers
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def http_env(tmp_path_factory):
    from minio_tpu.iam import IAMSys
    from minio_tpu.s3.admin import mount_admin
    from minio_tpu.s3.server import S3Server
    from tests.test_s3 import CREDS, REGION, S3TestClient
    root = tmp_path_factory.mktemp("tierdrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1,
                                   set_drive_count=4, parity=2,
                                   block_size=BLOCK)
    iam = IAMSys(sets, root_cred=CREDS)
    srv = S3Server(sets, creds=CREDS, region=REGION, iam=iam).start()
    mount_admin(srv)
    tiers = TierManager(sets)
    srv.api.tiers = tiers
    worker = TransitionWorker(sets, tiers, busy_fn=lambda: False).start()
    client = S3TestClient("127.0.0.1", srv.port)
    yield srv, client, tiers, worker, root
    worker.close()
    srv.stop()
    sets.close()


def test_admin_tier_crud_http(http_env):
    srv, client, tiers, _, root = http_env
    status, _, _ = client.request(
        "PUT", "/minio/admin/v3/tier",
        body=json.dumps({"name": "http-cold", "type": "fs",
                         "params": {"path": str(root / "ht")}}).encode())
    assert status == 200
    status, _, body = client.request("GET", "/minio/admin/v3/tier")
    assert status == 200
    doc = json.loads(body)
    assert any(t["name"] == "http-cold" for t in doc["tiers"])
    # duplicate add without force is a conflict
    status, _, body = client.request(
        "PUT", "/minio/admin/v3/tier",
        body=json.dumps({"name": "http-cold", "type": "fs",
                         "params": {"path": str(root / "ht")}}).encode())
    assert status == 409, body
    status, _, _ = client.request("DELETE", "/minio/admin/v3/tier",
                                  query={"name": "http-cold"})
    assert status == 200
    status, _, _ = client.request("DELETE", "/minio/admin/v3/tier",
                                  query={"name": "http-cold"})
    assert status == 404


def test_restore_object_http_flow(http_env):
    srv, client, tiers, worker, root = http_env
    tiers.add(TierConfig("cold", "fs", {"path": str(root / "t")}),
              update=True)
    status, _, _ = client.request("PUT", "/tierb")
    assert status == 200
    payload = os.urandom(120_000)
    status, headers, _ = client.request("PUT", "/tierb/doc", body=payload)
    assert status == 200
    etag = headers["etag"]

    worker.enqueue("tierb", "doc", "", "cold",
                   etag=etag.strip('"'))
    assert worker.drain(30), worker.stats()

    # GET answers 403 InvalidObjectState; HEAD shows tier + no restore
    status, _, body = client.request("GET", "/tierb/doc")
    assert status == 403 and b"InvalidObjectState" in body
    status, headers, _ = client.request("HEAD", "/tierb/doc")
    assert status == 200
    assert headers.get("x-amz-storage-class") == "cold"
    assert "x-amz-restore" not in headers

    # restore with a Days body; 202 on first, 200 on the extension
    body_xml = (b"<RestoreRequest><Days>2</Days></RestoreRequest>")
    status, _, body = client.request("POST", "/tierb/doc",
                                     query={"restore": ""},
                                     body=body_xml)
    assert status == 202, body
    status, _, _ = client.request("POST", "/tierb/doc",
                                  query={"restore": ""}, body=body_xml)
    assert status == 200

    status, headers, body = client.request("GET", "/tierb/doc")
    assert status == 200
    assert body == payload
    assert headers["etag"] == etag
    assert 'ongoing-request="false"' in headers.get("x-amz-restore", "")

    # malformed restore XML is rejected
    status, _, body = client.request("POST", "/tierb/doc",
                                     query={"restore": ""},
                                     body=b"<RestoreRequest><Days>")
    assert status == 400 and b"MalformedXML" in body

    # restore on a never-transitioned object: InvalidObjectState
    client.request("PUT", "/tierb/hot", body=b"hot")
    status, _, body = client.request("POST", "/tierb/hot",
                                     query={"restore": ""},
                                     body=body_xml)
    assert status == 403 and b"InvalidObjectState" in body

    # DELETE frees the remote copy
    md = srv.api.obj.get_object_info("tierb", "doc").user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    assert tiers.client("cold").head(remote_key) == len(payload)
    status, _, _ = client.request("DELETE", "/tierb/doc")
    assert status == 204
    with pytest.raises(TierObjectNotFound):
        tiers.client("cold").head(remote_key)


def test_madmin_tier_client(http_env):
    from minio_tpu.madmin import AdminClient
    from tests.test_s3 import CREDS
    srv, _, _, _, root = http_env
    mc = AdminClient("127.0.0.1", srv.port, CREDS.access_key,
                     CREDS.secret_key)
    mc.add_tier("sdk-cold", "fs", path=str(root / "sdk"))
    assert any(t["name"] == "sdk-cold" for t in mc.list_tiers())
    mc.add_tier("sdk-cold", "fs", update=True, path=str(root / "sdk2"))
    mc.remove_tier("sdk-cold")
    assert all(t["name"] != "sdk-cold" for t in mc.list_tiers())


def test_tier_metrics_registered(env):
    zz, tiers, worker, _ = env
    info = zz.put_object("b", "m", b"m" * 10_000)
    worker.enqueue("b", "m", "", "cold", etag=info.etag)
    assert worker.drain(30)
    from minio_tpu.utils import telemetry
    snap = telemetry.REGISTRY.snapshot("minio_tpu_tier_")
    objects = snap.get("minio_tpu_tier_objects_total", {})
    assert any("cold" in labels and v >= 1
               for labels, v in objects.items()), snap


def test_transition_commit_precondition_aborts_on_overwrite(env):
    """The stub-rewrite identity pin: a mismatching etag inside the
    write lock aborts the commit (the unversioned overwrite race)."""
    zz, tiers, _, _ = env
    zz.put_object("b", "race", b"current" * 100)
    with pytest.raises(api_errors.PreConditionFailed):
        zz.transition_object("b", "race", tier="cold",
                             remote_object="whatever",
                             expect_etag="not-the-etag")
    # and the object is untouched
    _, stream = zz.get_object("b", "race")
    assert b"".join(stream) == b"current" * 100


def test_admin_tier_delete_refuses_in_use(http_env):
    srv, client, tiers, _, root = http_env
    tiers.add(TierConfig("used", "fs", {"path": str(root / "used")}),
              update=True)
    client.request("PUT", "/usedb")
    lc = ('<LifecycleConfiguration><Rule><Status>Enabled</Status>'
          '<Prefix></Prefix><Transition><Days>9</Days>'
          '<StorageClass>used</StorageClass></Transition></Rule>'
          '</LifecycleConfiguration>')
    status, _, _ = client.request("PUT", "/usedb",
                                  query={"lifecycle": ""},
                                  body=lc.encode())
    assert status == 200
    status, _, body = client.request("DELETE", "/minio/admin/v3/tier",
                                     query={"name": "used"})
    assert status == 409 and b"TierBackendInUse" in body
    status, _, _ = client.request("DELETE", "/minio/admin/v3/tier",
                                  query={"name": "used",
                                         "force": "true"})
    assert status == 200


def test_versioned_null_delete_keeps_remote_copy(http_env):
    """DELETE ?versionId=null on a VERSIONED bucket writes a marker —
    the stub stays, so the remote copy must NOT be freed (the review's
    data-loss scenario)."""
    srv, client, tiers, worker, root = http_env
    tiers.add(TierConfig("cold", "fs", {"path": str(root / "t")}),
              update=True)
    client.request("PUT", "/verb")
    status, _, _ = client.request(
        "PUT", "/verb", query={"versioning": ""},
        body=b'<VersioningConfiguration><Status>Enabled</Status>'
             b'</VersioningConfiguration>')
    assert status == 200
    payload = b"versioned" * 2000
    status, h, _ = client.request("PUT", "/verb/doc", body=payload)
    assert status == 200
    vid = h["x-amz-version-id"]
    worker.enqueue("verb", "doc", vid, "cold",
                   etag=h["etag"].strip('"'))
    assert worker.drain(30)
    md = srv.api.obj.get_object_info(
        "verb", "doc",
        GetOptions(version_id=vid)).user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    # versionId=null on a versioned bucket: marker write, remote stays
    status, h, _ = client.request("DELETE", "/verb/doc",
                                  query={"versionId": "null"})
    assert status == 204 and h.get("x-amz-delete-marker") == "true"
    assert tiers.client("cold").head(remote_key) == len(payload)
    # targeted version delete DOES free it
    status, _, _ = client.request("DELETE", "/verb/doc",
                                  query={"versionId": vid})
    assert status == 204
    with pytest.raises(TierObjectNotFound):
        tiers.client("cold").head(remote_key)


def test_batch_delete_frees_remote_copies(http_env):
    srv, client, tiers, worker, root = http_env
    tiers.add(TierConfig("cold", "fs", {"path": str(root / "t")}),
              update=True)
    client.request("PUT", "/batchb")
    payload = b"bulk" * 3000
    status, h, _ = client.request("PUT", "/batchb/bulk1", body=payload)
    assert status == 200
    worker.enqueue("batchb", "bulk1", "", "cold",
                   etag=h["etag"].strip('"'))
    assert worker.drain(30)
    md = srv.api.obj.get_object_info("batchb", "bulk1").user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    body = (b'<Delete><Object><Key>bulk1</Key></Object>'
            b'<Object><Key>missing</Key></Object></Delete>')
    status, _, resp = client.request(
        "POST", "/batchb", query={"delete": ""}, body=body,
        headers={"content-md5": ""})
    assert status == 200, resp
    with pytest.raises(TierObjectNotFound):
        tiers.client("cold").head(remote_key)


def test_noncurrent_expiry_frees_remote(env):
    from minio_tpu.features.lifecycle import noncurrent_sweep_action
    zz, tiers, worker, _ = env
    old = zz.put_object("b", "ncx", b"old" * 800,
                        opts=PutOptions(versioned=True))
    time.sleep(0.01)
    zz.put_object("b", "ncx", b"new" * 800,
                  opts=PutOptions(versioned=True))
    worker.enqueue("b", "ncx", old.version_id, "cold", etag=old.etag)
    assert worker.drain(30)
    md = zz.get_object_info(
        "b", "ncx",
        GetOptions(version_id=old.version_id)).user_defined
    remote_key = md[dt.TRANSITIONED_OBJECT_KEY]
    lc = ("<LifecycleConfiguration><Rule><Status>Enabled</Status>"
          "<Prefix></Prefix><NoncurrentVersionExpiration>"
          "<NoncurrentDays>1</NoncurrentDays>"
          "</NoncurrentVersionExpiration></Rule>"
          "</LifecycleConfiguration>")
    act = noncurrent_sweep_action(FakeBucketMeta(lc), zz,
                                  now_fn=lambda: time.time() + 2 * DAY,
                                  tiers=tiers)
    act("b")
    with pytest.raises(api_errors.VersionNotFound):
        zz.get_object_info("b", "ncx",
                           GetOptions(version_id=old.version_id))
    with pytest.raises(TierObjectNotFound):
        tiers.client("cold").head(remote_key)


def test_rebalance_moves_transitioned_stub(tmp_path):
    """Decommissioning a pool holding a transitioned stub moves the
    xl.meta pointer (metadata-only) into the active pool; the object
    still restores from its unchanged remote copy afterwards."""
    zz = ErasureServerSets([make_sets(tmp_path, "p0", enable_mrf=False),
                            make_sets(tmp_path, "p1", enable_mrf=False)])
    try:
        zz.make_bucket("b")
        tiers = TierManager(zz)
        tiers.add(TierConfig("cold", "fs",
                             {"path": str(tmp_path / "tier")}))
        payload = os.urandom(120_000)
        # land the object in pool 0 specifically
        info = zz.server_sets[0].put_object("b", "stub", payload)
        worker = TransitionWorker(zz, tiers, **NEVER_BUSY).start()
        worker.enqueue("b", "stub", "", "cold", etag=info.etag)
        assert worker.drain(30), worker.stats()
        worker.close()
        md = zz.get_object_info("b", "stub").user_defined
        remote_key = md[dt.TRANSITIONED_OBJECT_KEY]

        zz.start_decommission(0, busy_fn=lambda: False,
                              throttle_s=0.001)
        deadline = time.time() + 60
        while time.time() < deadline:
            st = zz.rebalance_status().get("rebalance", {})
            if st.get("status") == "complete":
                break
            assert st.get("status") != "failed", st
            time.sleep(0.05)
        else:
            raise AssertionError(zz.rebalance_status())

        # the stub now lives in pool 1 only, still gated, still
        # pointing at the untouched remote copy
        assert not zz.server_sets[0].has_object_versions("b", "stub")
        assert zz.server_sets[1].has_object_versions("b", "stub")
        with pytest.raises(api_errors.InvalidObjectState):
            zz.get_object("b", "stub")
        assert tiers.client("cold").head(remote_key) == len(payload)
        restore_object(zz, tiers, "b", "stub")
        oi, stream = zz.get_object("b", "stub")
        assert b"".join(stream) == payload
        assert oi.etag == info.etag
    finally:
        zz.close()


# ---------------------------------------------------------------------------
# PR 10 satellites: async RestoreObject + part-boundary-preserving restores
# ---------------------------------------------------------------------------

def _transition_now(zz, tiers, worker, bucket, name, vid=""):
    """Transition one version through the worker and wait for it."""
    info = zz.get_object_info(bucket, name, GetOptions(version_id=vid))
    worker.enqueue(bucket, name, info.version_id, "cold",
                   etag=info.etag)
    assert worker.drain(30), worker.stats()
    return info


def test_multipart_restore_preserves_part_boundaries(env):
    """A transitioned MULTIPART object restores through a real
    multipart replay: the part list and the multipart etag survive the
    round-trip (not a single-part rewrite), bytes identical."""
    from minio_tpu.object.multipart import CompletePart
    zz, tiers, worker, _tmp = env
    p1, p2 = b"a" * (5 << 20), b"b" * (1 << 20)
    up = zz.new_multipart_upload("b", "mpr", PutOptions(versioned=True))
    e1 = zz.put_object_part("b", "mpr", up, 1, io.BytesIO(p1),
                            len(p1)).etag
    e2 = zz.put_object_part("b", "mpr", up, 2, io.BytesIO(p2),
                            len(p2)).etag
    info = zz.complete_multipart_upload(
        "b", "mpr", up, [CompletePart(1, e1), CompletePart(2, e2)])
    assert info.etag.endswith("-2")

    _transition_now(zz, tiers, worker, "b", "mpr", info.version_id)
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "mpr")
    stub = zz.get_object_info("b", "mpr")
    assert [(p.number, p.size) for p in stub.parts] == \
        [(1, len(p1)), (2, len(p2))]        # stub keeps the shape

    restore_object(zz, tiers, "b", "mpr", version_id=info.version_id)
    got = zz.get_object_info("b", "mpr")
    assert got.etag == info.etag            # multipart etag identical
    assert [(p.number, p.size) for p in got.parts] == \
        [(1, len(p1)), (2, len(p2))]
    assert got.version_id == info.version_id
    assert got.mod_time == info.mod_time
    oi, stream = zz.get_object("b", "mpr")
    assert b"".join(stream) == p1 + p2
    # ranged read across the preserved part boundary
    _, stream = zz.get_object("b", "mpr", offset=(5 << 20) - 2, length=4)
    assert b"".join(stream) == b"aabb"


def test_async_restore_background_pull_and_ongoing_gate(env):
    """The async RestoreObject path: mark ongoing + enqueue on the
    transition worker -> the version stays gated while ongoing, the
    background pull completes it, and a FAILED pull clears the marker
    so the client can retry (never RestoreAlreadyInProgress forever)."""
    from minio_tpu.tier.client import NaughtyTierClient
    from minio_tpu.tier.transition import (clear_restore_ongoing,
                                           mark_restore_ongoing)
    zz, tiers, worker, _tmp = env
    payload = os.urandom(1 << 18)
    zz.put_object("b", "bigr", payload, opts=PutOptions(versioned=True))
    info = _transition_now(zz, tiers, worker, "b", "bigr")

    # the 202 path: handler marks ongoing, worker pulls in background
    mark_restore_ongoing(zz, "b", "bigr")
    md = zz.get_object_info("b", "bigr").user_defined
    assert dt.RESTORE_ONGOING in md.get(dt.RESTORE_KEY, "")
    with pytest.raises(api_errors.InvalidObjectState):
        zz.get_object("b", "bigr")          # ongoing != restored
    assert worker.enqueue_restore("b", "bigr", info.version_id, days=2)
    assert worker.drain(30), worker.stats()
    assert worker.stats()["restored"] == 1
    oi, stream = zz.get_object("b", "bigr")
    assert b"".join(stream) == payload
    md = zz.get_object_info("b", "bigr").user_defined
    assert dt.RESTORE_ONGOING not in md.get(dt.RESTORE_KEY, "")

    # reclaim back to a stub, then a FAILED background pull clears the
    # ongoing marker instead of wedging future restores
    stub_md = md
    zz.transition_object(
        "b", "bigr", version_id=info.version_id, tier="cold",
        remote_object=stub_md[dt.TRANSITIONED_OBJECT_KEY],
        expect_etag=info.etag)
    naughty = NaughtyTierClient(tiers.client("cold"),
                                fail_verbs={"get": TierClientError("503")})
    tiers.set_client("cold", naughty)
    mark_restore_ongoing(zz, "b", "bigr")
    assert worker.enqueue_restore("b", "bigr", info.version_id, days=1)
    assert worker.drain(30), worker.stats()
    assert worker.stats()["restore_failed"] == 1
    md = zz.get_object_info("b", "bigr").user_defined
    assert dt.RESTORE_KEY not in md          # marker cleared: retryable
    naughty.clear_faults()
    restore_object(zz, tiers, "b", "bigr", version_id=info.version_id)
    oi, stream = zz.get_object("b", "bigr")
    assert b"".join(stream) == payload


def test_restore_http_async_202(http_env, monkeypatch):
    """Over HTTP: a RestoreObject at/above MINIO_TPU_RESTORE_ASYNC_BYTES
    answers 202 immediately with the pull running on the worker, a
    duplicate answers RestoreAlreadyInProgress (409) while the marker
    is up, and the object becomes readable once the background pull
    lands."""
    srv, client, tiers, worker, root = http_env
    tiers.add(TierConfig("cold", "fs", {"path": str(root / "t")}),
              update=True)
    monkeypatch.setenv("MINIO_TPU_RESTORE_ASYNC_BYTES", "1024")
    srv.api.restore_worker = worker
    try:
        client.request("PUT", "/asyb")
        payload = os.urandom(1 << 16)
        status, headers, _ = client.request("PUT", "/asyb/big",
                                            body=payload)
        assert status == 200
        worker.enqueue("asyb", "big", "", "cold",
                       etag=headers["etag"].strip('"'))
        assert worker.drain(30), worker.stats()

        body_xml = b"<RestoreRequest><Days>1</Days></RestoreRequest>"
        status, _, _ = client.request("POST", "/asyb/big",
                                      query={"restore": ""},
                                      body=body_xml)
        assert status == 202
        status2, _, body2 = client.request("POST", "/asyb/big",
                                           query={"restore": ""},
                                           body=body_xml)
        # either the pull already landed (200 window-extend) or the
        # ongoing gate answers RestoreAlreadyInProgress
        assert status2 in (200, 409), (status2, body2)
        if status2 == 409:
            assert b"RestoreAlreadyInProgress" in body2
        assert worker.drain(30), worker.stats()
        deadline = time.time() + 10
        while time.time() < deadline:
            status, headers, body = client.request("GET", "/asyb/big")
            if status == 200:
                break
            time.sleep(0.1)
        assert status == 200 and body == payload
        assert 'ongoing-request="false"' in headers.get("x-amz-restore",
                                                        "")
    finally:
        srv.api.restore_worker = None
