"""Gray-failure chaos: drives (and peers) that are SLOW while still
answering. A NaughtyDisk stall (the drive answers after 0.5+ s) drives
the three behaviors of the gray-failure plane:

  * adaptive hedged reads bound GET latency under a mid-GET stall,
  * quorum-ack writes bound PUT / multipart-commit latency under a
    mid-PUT stall, with zero acked-write loss once MRF drains,
  * the DiskMonitor quarantine walks the slow drive through
    suspect → probation → heal-verified re-admission, excluding it
    from read plans while convicted.

These are the fast seeded cases (tier-1); timing asserts use wide
margins (bounded-by < stall) so a loaded CI box cannot flake them.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from minio_tpu.object.background import DiskMonitor
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage import XLStorage
from minio_tpu.storage.naughty import FaultSchedule, NaughtyDisk
from minio_tpu.utils import healthtrack

pytestmark = pytest.mark.chaos

K, M = 4, 2
NDISKS = K + M
BLOCK = 1 << 16
STALL = 0.6

READ_STALLS = ("read_file_stream", "read_file", "read_all")
WRITE_STALLS = ("append_file", "create_file", "write_all",
                "write_metadata", "rename_data", "rename_file")

MRF_TEST_OPTIONS = dict(max_retries=10, backoff_base=0.02,
                        backoff_max=0.25)


@pytest.fixture(autouse=True)
def _gray_env(monkeypatch):
    """Tight adaptive deadlines so the plane bites at test scale, and
    a clean tracker so one test's convictions never leak into the
    next."""
    monkeypatch.setenv("MINIO_TPU_HEDGE_FLOOR_S", "0.05")
    monkeypatch.setenv("MINIO_TPU_HEDGE_CEIL_S", "0.1")
    monkeypatch.setenv("MINIO_TPU_WRITE_STALL_FLOOR_S", "0.1")
    monkeypatch.setenv("MINIO_TPU_WRITE_STALL_CEIL_S", "0.2")
    healthtrack.TRACKER.reset()
    yield
    healthtrack.TRACKER.reset()


def payload(size: int, seed: int = 11) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def make_sets(tmp_path, n: int = NDISKS, parity: int = M
              ) -> tuple[ErasureSets, NaughtyDisk]:
    """1 set x n drives, drive 0 wrapped in a (disarmed) NaughtyDisk."""
    drives: list = [XLStorage(str(tmp_path / f"d{j}"))
                    for j in range(n)]
    nd = NaughtyDisk(drives[0], enabled=False)
    drives[0] = nd
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=n, parity=parity,
        block_size=BLOCK, mrf_options=dict(MRF_TEST_OPTIONS))
    sets.make_bucket("b")
    return sets, nd


def stall_on(nd: NaughtyDisk, verbs, dur: float = STALL) -> None:
    nd.stall_verbs = {v: dur for v in verbs}
    nd.arm()


def assert_converged(sets: ErasureSets, datas: dict) -> None:
    """Every acked write reads back byte-identical and every shard is
    whole on every drive (the no-acked-write-loss bar)."""
    assert sets.drain_mrf(30.0)
    assert sets.mrf_stats()["pending"] == 0
    for name, data in datas.items():
        _, it = sets.get_object("b", name)
        assert b"".join(it) == data, name
        for d in sets.sets[0].disks:
            fi = d.read_version("b", name)
            d.check_parts("b", name, fi)


def test_stall_mid_get_bounded(tmp_path):
    """A drive stalling every read answers the GET anyway — the hedged
    reader races a spare shard read at the adaptive deadline and the
    client never waits out the stall."""
    sets, nd = make_sets(tmp_path)
    data = payload(3 * BLOCK + 123)
    sets.put_object("b", "o", data)
    from minio_tpu.utils.telemetry import REGISTRY
    hedged = REGISTRY.counter("minio_tpu_hedged_reads_total")
    before = hedged.value(trigger="latency")
    stall_on(nd, READ_STALLS)
    try:
        t0 = time.perf_counter()
        _, it = sets.get_object("b", "o")
        got = b"".join(it)
        dt = time.perf_counter() - t0
    finally:
        nd.disarm()
        nd.stall_verbs = {}
    assert got == data
    assert dt < STALL * 0.75, f"GET took {dt:.3f}s against {STALL}s stall"
    assert nd.stats.stalls >= 1          # the stall really fired
    assert hedged.value(trigger="latency") > before
    # a latency hedge is NOT damage: nothing was queued for heal
    assert sets.mrf_stats()["pending"] == 0


def test_hedge_loser_stays_benign_across_groups(tmp_path):
    """A reader condemned by a latency hedge in an early read group
    stays benign-missing for every LATER group of the same stream: a
    multi-group GET against a gray drive must not flag a degraded-read
    heal for shards that are perfectly intact on disk (review
    regression — the single-group case can't catch it)."""
    from minio_tpu.object.engine import GET_BATCH_BLOCKS
    sets, nd = make_sets(tmp_path)
    # 3 read groups' worth of blocks
    data = payload(3 * GET_BATCH_BLOCKS * BLOCK + 31, seed=15)
    sets.put_object("b", "o", data)
    stall_on(nd, READ_STALLS)
    try:
        _, it = sets.get_object("b", "o")
        got = b"".join(it)
    finally:
        nd.disarm()
        nd.stall_verbs = {}
    assert got == data
    assert nd.stats.stalls >= 1
    # plan-caused misses across EVERY group: nothing queued for heal
    assert sets.mrf_stats()["pending"] == 0
    assert sets.mrf_stats()["queued"] == 0
    sets.close()


def test_stall_mid_put_quorum_ack(tmp_path):
    """A drive stalling every write: the PUT acks once quorum is
    durable, the laggard is abandoned to the background lane, and MRF
    converges the object back to full redundancy — zero acked-write
    loss."""
    sets, nd = make_sets(tmp_path)
    data = payload(2 * BLOCK + 77, seed=12)
    stall_on(nd, WRITE_STALLS)
    try:
        t0 = time.perf_counter()
        sets.put_object("b", "o", data)
        dt = time.perf_counter() - t0
    finally:
        nd.disarm()
        nd.stall_verbs = {}
    # without quorum-ack this path pays >= 2 stalls (append flush +
    # meta/rename); with it the ack is bounded by the stall grace
    assert dt < STALL * 1.5, f"PUT took {dt:.3f}s"
    assert nd.stats.stalls >= 1
    assert_converged(sets, {"o": data})
    sets.close()


def test_stall_mid_multipart_commit(tmp_path):
    """CompleteMultipartUpload's rename fan-out acks at quorum under a
    stalled drive, and the commit converges through MRF."""
    sets, nd = make_sets(tmp_path)
    data = payload(3 * BLOCK + 17, seed=13)
    uid = sets.new_multipart_upload("b", "mp")
    sets.put_object_part("b", "mp", uid, 1, data)
    from minio_tpu.object.multipart import CompletePart
    pi = sets.list_object_parts("b", "mp", uid)[0]
    stall_on(nd, WRITE_STALLS)
    try:
        t0 = time.perf_counter()
        sets.complete_multipart_upload(
            "b", "mp", uid, [CompletePart(1, pi.etag)])
        dt = time.perf_counter() - t0
    finally:
        nd.disarm()
        nd.stall_verbs = {}
    assert dt < STALL * 1.5, f"complete took {dt:.3f}s"
    assert nd.stats.stalls >= 1
    assert_converged(sets, {"mp": data})
    sets.close()


def test_slow_peer_behind_storage_rpc(tmp_path):
    """A slow REMOTE drive: the stall is injected on the server side
    of storage_rpc, so the whole gray-read crosses the wire — the
    hedged reader must race a stalled PEER exactly like a stalled
    local drive."""
    from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                                   StorageRPCServer)
    from minio_tpu.distributed.transport import RPCServer

    ak, sk = "graykey", "graysecret1234"
    serving: dict = {}
    naughty = None
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j == 0:
            naughty = NaughtyDisk(d, enabled=False)
            serving[f"/d{j}"] = naughty
        else:
            serving[f"/d{j}"] = d
    rpc_srv = StorageRPCServer(serving, ak, sk)
    host = RPCServer().start()
    host.mount(rpc_srv.handler)
    remotes = [RemoteStorage("127.0.0.1", host.port, f"/d{j}", ak, sk)
               for j in range(NDISKS)]
    sets = ErasureSets.from_storage(
        remotes, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK, sources=list(remotes),
        mrf_options=dict(MRF_TEST_OPTIONS))
    sets.make_bucket("b")
    try:
        data = payload(2 * BLOCK + 5, seed=14)
        sets.put_object("b", "o", data)
        stall_on(naughty, READ_STALLS)
        t0 = time.perf_counter()
        _, it = sets.get_object("b", "o")
        got = b"".join(it)
        dt = time.perf_counter() - t0
        naughty.disarm()
        naughty.stall_verbs = {}
        assert got == data
        assert dt < STALL * 0.75, f"remote GET took {dt:.3f}s"
        assert naughty.stats.stalls >= 1
    finally:
        sets.close()
        host.stop()


def test_quarantine_probation_readmission_roundtrip(tmp_path,
                                                    monkeypatch):
    """The full state machine: slow traffic convicts the drive
    (suspect), read plans then exclude it entirely, probation probes
    fail while it still stalls and pass once it recovers, and
    re-admission is heal-verified + kicks MRF."""
    monkeypatch.setenv("MINIO_TPU_QUAR_LATENCY_S", "0.2")
    monkeypatch.setenv("MINIO_TPU_QUAR_MIN_SAMPLES", "4")
    monkeypatch.setenv("MINIO_TPU_QUAR_PROBATION_S", "0")
    monkeypatch.setenv("MINIO_TPU_QUAR_PROBES", "2")
    sets, nd = make_sets(tmp_path)
    key = healthtrack.disk_key(nd)
    datas = {}
    for i in range(4):
        datas[f"o{i}"] = payload(BLOCK + i, seed=20 + i)
        sets.put_object("b", f"o{i}", datas[f"o{i}"])
    mon = DiskMonitor(sets, interval=3600)   # manual scans only
    stall_on(nd, READ_STALLS + ("disk_info",))
    for i in range(4):                       # slow traffic = evidence
        _, it = sets.get_object("b", f"o{i}")
        b"".join(it)
    mon.scan_once()
    assert healthtrack.TRACKER.state_of("drive", key) == \
        healthtrack.STATE_SUSPECT

    # convicted: reads exclude the drive entirely AND stay fast
    calls0 = dict(nd.stats.calls)
    t0 = time.perf_counter()
    _, it = sets.get_object("b", "o1")
    got = b"".join(it)
    dt = time.perf_counter() - t0
    assert got == datas["o1"]
    assert dt < 0.3, f"quarantined GET took {dt:.3f}s"
    for v in READ_STALLS:
        assert nd.stats.calls.get(v, 0) == calls0.get(v, 0), v

    # still stalling: the probation probe re-convicts
    mon.scan_once()
    assert healthtrack.TRACKER.state_of("drive", key) in (
        healthtrack.STATE_SUSPECT, healthtrack.STATE_PROBATION)

    # recovery: probes pass, re-admission is heal-verified + MRF kicks
    nd.disarm()
    nd.stall_verbs = {}
    for _ in range(4):
        mon.scan_once()
        if healthtrack.TRACKER.state_of("drive", key) == \
                healthtrack.STATE_OK:
            break
    assert healthtrack.TRACKER.state_of("drive", key) == \
        healthtrack.STATE_OK
    events = [e for _k, e in mon.quarantine_events]
    assert events[:1] == ["suspect"] and events[-1] == "readmit"
    assert "probation" in events
    # re-admission cleared the pre-recovery evidence: the very next
    # scans must NOT re-convict off stale slow samples (the perpetual
    # flap + full-sweep loop a review round caught)
    mon.scan_once()
    mon.scan_once()
    assert healthtrack.TRACKER.state_of("drive", key) == \
        healthtrack.STATE_OK
    assert events.count("suspect") == 1
    assert_converged(sets, datas)
    sets.close()


def test_quarantine_capacity_rule(tmp_path):
    """With fewer than k healthy readers the plan keeps the suspect
    drive in play — quarantine must never turn a readable object
    unreadable."""
    sets, nd = make_sets(tmp_path)
    data = payload(BLOCK + 9, seed=30)
    sets.put_object("b", "o", data)
    key = healthtrack.disk_key(nd)
    healthtrack.TRACKER.set_state("drive", key,
                                  healthtrack.STATE_SUSPECT)
    # kill parity-count OTHER drives: only k drives remain, one of
    # them the suspect — it must still serve
    eng = sets.sets[0]
    killed = 0
    for j in range(len(eng.disks) - 1, 0, -1):
        if killed == M:
            break
        eng.disks[j] = None
        killed += 1
    _, it = sets.get_object("b", "o")
    assert b"".join(it) == data
    sets.close()


def test_schedule_stalls_deterministic():
    """Seeded stall schedule: same seed, same decisions; heavy tail
    capped at stall_max_s; op-count windows stall unconditionally."""
    s1 = FaultSchedule(seed=42, stall_rate=0.3, stall_s=0.2,
                       stall_pareto=1.0, stall_max_s=1.5)
    s2 = FaultSchedule(seed=42, stall_rate=0.3, stall_s=0.2,
                       stall_pareto=1.0, stall_max_s=1.5)
    seq1 = [s1.stall_for("read_file", n, 0) for n in range(200)]
    assert seq1 == [s2.stall_for("read_file", n, 0)
                    for n in range(200)]
    fired = [d for d in seq1 if d > 0]
    assert fired and all(d <= 1.5 for d in fired)
    assert any(d > 0.2 for d in fired)      # the tail is heavy
    win = FaultSchedule(seed=1, stall_s=0.3,
                        stall_windows=((10, 20),))
    assert win.stall_for("read_file", 1, 15) == pytest.approx(0.3)
    assert win.stall_for("read_file", 1, 25) == 0.0


def test_naughty_counts_stalls(tmp_path):
    d = XLStorage(str(tmp_path / "d0"))
    nd = NaughtyDisk(d, enabled=True)
    nd.verb_stalls = {"make_vol": {1: 0.05}}
    t0 = time.perf_counter()
    nd.make_vol("v1")
    assert time.perf_counter() - t0 >= 0.05
    nd.make_vol("v2")                        # one-shot: second is fast
    assert nd.stats.stalls == 1
    assert nd.stats.stall_s == pytest.approx(0.05)
