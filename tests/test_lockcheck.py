"""Lock-order watchdog: cycle detection, blocking flags, and the PR 6
mesh-dispatch deadlock shape as a regression test.

The conftest autouse fixture runs this module with the watchdog ON
(MINIO_TPU_LOCKCHECK=on) — the same wiring the chaos/concurrency
suites get — so these tests also prove that wiring works.
"""

from __future__ import annotations

import threading

import pytest

from minio_tpu.utils import lockcheck


@pytest.fixture(autouse=True)
def _fresh_graph():
    lockcheck.reset()
    yield
    # cycle-producing tests must not trip the module-level watchdog
    # assert in conftest
    lockcheck.reset()


def _in_thread(fn):
    box: list = []

    def run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — surfaced by caller
            box.append(e)

    t = threading.Thread(target=run)
    t.start()
    t.join(5)
    assert not t.is_alive(), "helper thread wedged"
    return box


def test_watchdog_enabled_by_conftest():
    assert lockcheck.enabled()


def test_ab_ba_cycle_detected_and_raised():
    a = lockcheck.mutex("t.A")
    b = lockcheck.mutex("t.B")
    with a:
        with b:
            pass
    # opposite nesting on another thread closes the cycle — detected
    # from the RECORDED graph, no unlucky interleaving required
    errs = _in_thread(lambda: _nest(b, a))
    assert len(errs) == 1 and isinstance(errs[0], lockcheck.LockOrderError)
    msg = str(errs[0])
    assert "t.A" in msg and "t.B" in msg and "cycle" in msg
    kinds = [v.kind for v in lockcheck.violations()]
    assert "cycle" in kinds


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def test_same_order_never_flags():
    a = lockcheck.mutex("t.A")
    b = lockcheck.mutex("t.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert _in_thread(lambda: _nest(a, b)) == []
    assert lockcheck.violations() == []


def test_reentrant_same_role_is_not_a_cycle():
    r = lockcheck.rlock("t.R")
    with r:
        with r:
            pass
    assert lockcheck.violations() == []


def test_three_lock_cycle_via_path():
    a, b, c = (lockcheck.mutex(f"t.{n}") for n in "ABC")
    _nest(a, b)
    _nest(b, c)
    errs = _in_thread(lambda: _nest(c, a))
    assert errs and isinstance(errs[0], lockcheck.LockOrderError)
    path = lockcheck.violations("cycle")[0].path
    assert path[0] == path[-1] or set(path) >= {"t.A", "t.B", "t.C"}


def test_record_only_mode(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_LOCKCHECK_RAISE", "off")
    lockcheck.refresh()
    try:
        a = lockcheck.mutex("t.A")
        b = lockcheck.mutex("t.B")
        _nest(a, b)
        assert _in_thread(lambda: _nest(b, a)) == []   # recorded, no raise
        assert lockcheck.violations("cycle")
    finally:
        monkeypatch.setenv("MINIO_TPU_LOCKCHECK_RAISE", "on")
        lockcheck.refresh()


def test_held_while_blocking_flagged(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_LOCKCHECK_BLOCK_MS", "50")
    lockcheck.refresh()
    try:
        outer = lockcheck.mutex("t.outer")
        contended = lockcheck.mutex("t.contended")
        release = threading.Event()
        started = threading.Event()

        def holder():
            with contended:
                started.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        started.wait(5)
        with outer:                      # holding outer ...
            threading.Timer(0.2, release.set).start()
            with contended:              # ... while blocking >50ms here
                pass
        t.join(5)
        kinds = {v.kind for v in lockcheck.violations()}
        assert "held-while-blocking" in kinds
        v = lockcheck.violations("held-while-blocking")[0]
        assert v.lock == "t.contended" and "t.outer" in v.held
    finally:
        lockcheck.refresh()


def test_long_hold_flagged(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_LOCKCHECK_HELD_MS", "40")
    lockcheck.refresh()
    try:
        m = lockcheck.mutex("t.slowhold")
        import time
        with m:
            time.sleep(0.1)
        vs = lockcheck.violations("long-hold")
        assert vs and vs[0].lock == "t.slowhold"
    finally:
        lockcheck.refresh()


def test_mutex_self_deadlock_flagged_not_hung():
    """Re-acquiring a held non-reentrant mutex on the same thread is
    the simplest deadlock — the inner acquire would block forever
    BEFORE any recording, so the wrapper flags it up front."""
    m = lockcheck.mutex("t.self")
    with m:
        with pytest.raises(lockcheck.LockOrderError, match="self-deadlock"):
            m.acquire()
    # releasable and reusable afterwards
    with m:
        pass
    assert any(v.kind == "cycle" and v.lock == "t.self"
               for v in lockcheck.violations())


def test_condition_is_reentrant_like_threading_default():
    """lockcheck.condition matches threading.Condition()'s default
    RLock semantics: nested `with cond:` must not deadlock, and a
    wait() at depth 2 fully releases so another thread can notify."""
    c = lockcheck.condition("t.recond")
    with c:
        with c:                      # reentrant — plain Condition() allows this
            pass
    woke = threading.Event()

    def notifier():
        with c:
            c.notify_all()

    with c:
        with c:
            threading.Timer(0.05, lambda: threading.Thread(
                target=notifier).start()).start()
            assert c.wait(5)         # depth-2 wait releases both levels
            woke.set()
    assert woke.is_set()
    assert lockcheck.violations("cycle") == []


def test_cycle_rollback_leaves_lock_usable():
    """A cycle-raising acquire rolls back fully: the same thread's
    next legitimate acquire of the (free) mutex must not be a
    spurious self-deadlock."""
    a = lockcheck.mutex("t.A")
    b = lockcheck.mutex("t.B")
    _nest(a, b)

    def ba():
        with b:
            try:
                a.acquire()
            except lockcheck.LockOrderError:
                pass
            # the rollback released the inner lock and cleared owner:
            # a plain acquire with nothing held must succeed cleanly
        with a:
            pass

    assert _in_thread(ba) == []


def test_flip_off_mid_hold_does_not_poison_later_runs():
    """A lock acquired while the watchdog is on and released after
    refresh(off) must still unwind its held-stack entry — otherwise
    this thread 'holds' the role forever in later enabled runs."""
    import os
    m = lockcheck.mutex("t.flip")
    other = lockcheck.mutex("t.other")
    m.acquire()
    os.environ["MINIO_TPU_LOCKCHECK"] = "off"
    lockcheck.refresh()
    m.release()                      # watchdog off: must still pop
    os.environ["MINIO_TPU_LOCKCHECK"] = "on"
    lockcheck.refresh()
    lockcheck.reset()
    with other:                      # no phantom t.flip -> t.other edge
        pass
    assert lockcheck.graph() == {}
    assert lockcheck.violations() == []


def test_condition_wait_drops_hold():
    """cond.wait releases the underlying lock through the checked
    protocol: another thread can acquire mid-wait, and no
    held-while-blocking/long-hold accrues against the waiter."""
    c = lockcheck.condition("t.cond")
    entered = threading.Event()

    def waker():
        entered.wait(5)
        with c:
            c.notify_all()

    t = threading.Thread(target=waker)
    t.start()
    with c:
        entered.set()
        assert c.wait(5)
    t.join(5)
    assert lockcheck.violations("cycle") == []


def test_disabled_watchdog_records_nothing(monkeypatch):
    monkeypatch.setenv("MINIO_TPU_LOCKCHECK", "off")
    lockcheck.refresh()
    try:
        a = lockcheck.mutex("t.A")
        b = lockcheck.mutex("t.B")
        _nest(a, b)
        assert _in_thread(lambda: _nest(b, a)) == []
        assert lockcheck.violations() == []
    finally:
        monkeypatch.setenv("MINIO_TPU_LOCKCHECK", "on")
        lockcheck.refresh()


# ---------------------------------------------------------------------------
# the PR 6 regression: concurrent mesh dispatch
# ---------------------------------------------------------------------------

def test_mesh_dispatch_cycle_shape_regression():
    """The PR 6 incident shape: the batch former's collector enters the
    serialized mesh-dispatch critical section while holding scheduler
    state, and a scheduler-bypass caller inside the dispatch section
    calls back into scheduler state (stats/occupancy). Before the
    watchdog this deadlocked an unlucky interleaving of the saturation
    A/B; now the second nesting order is flagged the FIRST time it is
    recorded, interleaving or not."""
    sched_mu = lockcheck.mutex("sched.buckets")
    dispatch_mu = lockcheck.mutex("mesh.dispatch")

    # thread 1 — the former: scheduler bookkeeping, then device launch
    def former():
        with sched_mu:
            with dispatch_mu:
                pass                      # mesh_put_batch(...)

    assert _in_thread(former) == []

    # thread 2 — the bypass caller: inside the dispatch guard, reads
    # scheduler occupancy (stats() takes the scheduler lock)
    def bypass():
        with dispatch_mu:
            with sched_mu:
                pass                      # scheduler.stats()

    errs = _in_thread(bypass)
    assert errs and isinstance(errs[0], lockcheck.LockOrderError)
    v = lockcheck.violations("cycle")[0]
    assert {"sched.buckets", "mesh.dispatch"} <= set(v.path)


def test_real_scheduler_and_metacache_clean_under_watchdog(tmp_path):
    """In-situ negative test: the instrumented production locks
    (scheduler buckets/kick, metacache cond, bpool, MRF queue) run a
    real submit/record/drain workload under the watchdog without a
    single cycle — the tree's lock orders are consistent."""
    import numpy as np
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.parallel.bpool import BytePool
    from minio_tpu.object.codec import Codec

    sched = BatchScheduler(max_batch=8, max_wait=0.001)
    try:
        codec = Codec(2, 1, 1 << 12)
        from minio_tpu import bitrot as bitrot_mod
        futs = [sched.submit(codec,
                             np.zeros((1, 2, 1 << 11), np.uint8),
                             bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256)
                for _ in range(4)]
        for f in futs:
            f.result(5)        # CPU host declines or dispatches — either way resolves
        pool = BytePool(1 << 10, 2)
        b1 = pool.get(1)
        pool.put(b1)
        sched.stats()
    finally:
        sched.close()
    assert lockcheck.violations("cycle") == []
