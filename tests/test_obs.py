"""Cluster observability plane (ISSUE 13): federated metrics scrape,
live cluster trace streaming, device-dispatch attribution, edge-loop
observability, /spans filters, and edge/threaded trace parity.

The multi-node harness runs two real ClusterNodes on loopback ports
(the test_cluster pattern) and proves the acceptance list:

  1. the ?cluster=1 exposition equals the bucket-wise merge of the
     per-node registries (counters summed, node labels on gauges), and
     a KILLED peer yields a degraded-but-successful scrape with
     `minio_tpu_cluster_scrape_failed_total{node}` counted;
  2. a ?follow=1 trace stream opened on node A delivers a request
     served by node B — on both frontends — and a client disconnect
     unwinds every peer subscription without leaking a worker thread;
  3. dispatch-stage histograms show a nonzero queue/transfer/compute
     split and pass the exposition lint.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import re
import socket
import threading
import time
import urllib.parse

import pytest

from minio_tpu.cluster import ClusterNode, NodeSpec
from minio_tpu.madmin import AdminClient
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.admin import mount_admin
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server
from minio_tpu.utils import promfed, telemetry

CREDS = Credentials("obstestkey123", "obstestsecret1234")
REGION = "us-east-1"


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _boot_cluster(tmp_path, edge: bool = True):
    """Two real nodes, booted concurrently (bootstrap verify needs
    both listening)."""
    import os
    ports = _free_ports(2)
    nodes = [NodeSpec("127.0.0.1", ports[i],
                      [str(tmp_path / f"n{i}d{j}") for j in range(2)])
             for i in range(2)]
    out: list = [None, None]
    errs: list = [None, None]
    was = os.environ.get("MINIO_TPU_EDGE")
    os.environ["MINIO_TPU_EDGE"] = "on" if edge else "off"
    try:
        def boot(i):
            try:
                out[i] = ClusterNode(nodes, i, CREDS, parity=1,
                                     set_drive_count=4,
                                     block_size=1 << 16,
                                     format_timeout=60.0)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs[i] = e

        threads = [threading.Thread(target=boot, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        if was is None:
            os.environ.pop("MINIO_TPU_EDGE", None)
        else:
            os.environ["MINIO_TPU_EDGE"] = was
    for e in errs:
        if e is not None:
            raise e
    assert all(o is not None for o in out)
    return out


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    nodes = _boot_cluster(tmp_path_factory.mktemp("obscluster"))
    yield nodes
    for n in nodes:
        try:
            n.shutdown()
        except Exception:  # noqa: BLE001 — second shutdown of a node
            pass           # the kill test already stopped


def _signed_request(port, method, path, query=None, body=b""):
    query = {k: [v] for k, v in (query or {}).items()}
    qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
    hdrs = sig.sign_v4(method, path, query,
                       {"host": f"127.0.0.1:{port}"},
                       hashlib.sha256(body).hexdigest(), CREDS, REGION)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path + (f"?{qs}" if qs else ""), body=body,
                 headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _mc(node) -> AdminClient:
    return AdminClient("127.0.0.1", node.spec.port, CREDS.access_key,
                       CREDS.secret_key)


def _follow_pumps() -> list:
    return [t for t in threading.enumerate()
            if t.name == "trace-follow-peer" and t.is_alive()]


def _await_no_pumps(deadline_s: float = 12.0) -> None:
    deadline = time.monotonic() + deadline_s
    while _follow_pumps() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert not _follow_pumps(), (
        "peer trace subscriptions leaked pump threads: "
        + ", ".join(t.name for t in _follow_pumps()))


# ---------------------------------------------------------------------------
# 1. federated metrics scrape
# ---------------------------------------------------------------------------

PEER_EXPO = """# HELP minio_obs_fed_total synthetic ops
# TYPE minio_obs_fed_total counter
minio_obs_fed_total{api="x"} 5
# HELP minio_obs_fed_depth synthetic queue depth
# TYPE minio_obs_fed_depth gauge
minio_obs_fed_depth 7
# HELP minio_obs_fed_seconds synthetic latency
# TYPE minio_obs_fed_seconds histogram
minio_obs_fed_seconds_bucket{le="0.1"} 2
minio_obs_fed_seconds_bucket{le="+Inf"} 4
minio_obs_fed_seconds_sum 1.5
minio_obs_fed_seconds_count 4
"""


def test_cluster_scrape_is_bucketwise_merge(cluster):
    """The ?cluster=1 exposition equals promfed's merge of the
    per-node registries: counters summed (no node label), gauges
    node-labelled, histograms bucket-wise summed. Node B's exposition
    is stubbed (in one process both nodes share the registry, so the
    REAL per-node divergence a deployment has must be injected)."""
    a, b = cluster
    # local (node A) side of the synthetic family
    telemetry.REGISTRY.counter("minio_obs_fed_total",
                               "synthetic ops").inc(3, api="x")
    telemetry.REGISTRY.gauge("minio_obs_fed_depth",
                             "synthetic queue depth").set(2)
    h = telemetry.REGISTRY.histogram("minio_obs_fed_seconds",
                                     "synthetic latency",
                                     buckets=(0.1,))
    h.observe(0.05)
    b._peer_rpc.get_metrics_text = lambda: PEER_EXPO
    merged = _mc(a).cluster_metrics()

    fams = promfed.parse_exposition(merged)
    # counter summed across nodes: 3 (A) + 5 (B stub)
    assert fams["minio_obs_fed_total"].samples[
        ("minio_obs_fed_total", (("api", "x"),))] == 8
    # gauges: one series per node, node label attached
    depth = fams["minio_obs_fed_depth"].samples
    assert depth[("minio_obs_fed_depth",
                  (("node", a.spec.addr),))] == 2
    assert depth[("minio_obs_fed_depth",
                  (("node", b.spec.addr),))] == 7
    # histogram bucket-wise: A contributes 1 obs in le=0.1, B stubs 2/4
    lat = fams["minio_obs_fed_seconds"].samples
    assert lat[("minio_obs_fed_seconds_bucket",
                (("le", "0.1"),))] == 3
    assert lat[("minio_obs_fed_seconds_bucket",
                (("le", "+Inf"),))] == 5
    assert lat[("minio_obs_fed_seconds_count", ())] == 5
    # ... and the endpoint output IS the library merge of the same
    # inputs (the acceptance equality, not just spot samples)
    local_text = a.admin.metrics.local_text()
    expect = promfed.merge_expositions(
        [(a.spec.addr, local_text), (b.spec.addr, PEER_EXPO)])
    exp_fams = promfed.parse_exposition(expect)
    for name in ("minio_obs_fed_total", "minio_obs_fed_depth",
                 "minio_obs_fed_seconds"):
        assert fams[name].samples == exp_fams[name].samples, name


def test_cluster_scrape_deadline_bounded(cluster):
    """A peer that answers too slowly counts as scrape-failed: the
    per-peer deadline bounds the whole federated scrape."""
    import os
    a, b = cluster

    def slow():
        time.sleep(5.0)
        return PEER_EXPO

    b._peer_rpc.get_metrics_text = slow
    was = os.environ.get("MINIO_TPU_CLUSTER_SCRAPE_S")
    os.environ["MINIO_TPU_CLUSTER_SCRAPE_S"] = "0.5"
    shed = telemetry.REGISTRY.counter(
        "minio_tpu_cluster_scrape_failed_total")
    before = shed.value(node=b.spec.addr)
    try:
        t0 = time.monotonic()
        merged = _mc(a).cluster_metrics()
        assert time.monotonic() - t0 < 4.0
    finally:
        if was is None:
            os.environ.pop("MINIO_TPU_CLUSTER_SCRAPE_S", None)
        else:
            os.environ["MINIO_TPU_CLUSTER_SCRAPE_S"] = was
        b._peer_rpc.get_metrics_text = lambda: PEER_EXPO
    assert shed.value(node=b.spec.addr) == before + 1
    assert "minio_tpu_cluster_scrape_failed_total" in merged
    # the timed-out scrape tripped the peer transport offline (that is
    # the transport's deadline semantics); wait for the health probe to
    # re-admit it so later tests see a whole cluster
    deadline = time.monotonic() + 20
    while not all(p.online for p in a.notification.peers) and \
            time.monotonic() < deadline:
        time.sleep(0.2)
    assert all(p.online for p in a.notification.peers)


# ---------------------------------------------------------------------------
# 2. live cluster trace streaming
# ---------------------------------------------------------------------------

def test_follow_delivers_peer_records_and_unwinds(cluster):
    """A ?follow=1 stream on node A delivers a request SERVED BY node
    B (peer subscription grafting), then ends without leaking the
    pump threads."""
    a, b = cluster
    got: list = []
    t = threading.Thread(
        target=lambda: got.extend(
            _mc(a).trace_follow(count=1, api="PutObject", timeout=60)),
        daemon=True)
    t.start()
    time.sleep(0.8)                    # peer subscriptions armed
    st, _ = _signed_request(b.spec.port, "PUT", "/obsfollow")
    assert st == 200
    st, _ = _signed_request(b.spec.port, "PUT", "/obsfollow/obj",
                            body=b"follow me")
    assert st == 200
    t.join(timeout=20)
    assert not t.is_alive(), "follow stream never delivered"
    assert got and got[0]["api"] == "PutObject"
    assert got[0]["node"] == b.spec.addr, got[0]
    assert "ttfb_ms" in got[0]
    _await_no_pumps()


def test_follow_disconnect_frees_workers(cluster):
    """A client that vanishes mid-follow must unwind the server-side
    subscription (heartbeat write fails -> generator closes -> peer
    pumps exit) — no worker thread leaks."""
    a, _b = cluster
    path = "/minio/admin/v3/trace"
    query = {"follow": ["1"]}
    qs = urllib.parse.urlencode({"follow": "1"})
    hdrs = sig.sign_v4("GET", path, query,
                       {"host": f"127.0.0.1:{a.spec.port}"},
                       hashlib.sha256(b"").hexdigest(), CREDS, REGION)
    s = socket.create_connection(("127.0.0.1", a.spec.port),
                                 timeout=10)
    head = f"GET {path}?{qs} HTTP/1.1\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in hdrs.items()) + "\r\n"
    s.sendall(head.encode())
    buf = s.recv(4096)                 # headers (+ maybe a heartbeat)
    assert b"200" in buf.split(b"\r\n", 1)[0]
    deadline = time.monotonic() + 10
    while not _follow_pumps() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _follow_pumps(), "peer subscription never opened"
    s.close()                          # client dies
    _await_no_pumps()


def test_follow_threaded_frontend(tmp_path_factory):
    """The same cross-node follow delivery on the THREADED frontend
    (the byte-level oracle must hold the stream too)."""
    nodes = _boot_cluster(tmp_path_factory.mktemp("obsthreaded"),
                          edge=False)
    a, b = nodes
    try:
        assert not a.s3.edge_enabled
        got: list = []
        t = threading.Thread(
            target=lambda: got.extend(
                _mc(a).trace_follow(count=1, api="PutObject",
                                    timeout=60)),
            daemon=True)
        t.start()
        time.sleep(0.8)
        st, _ = _signed_request(b.spec.port, "PUT", "/obsthr")
        assert st == 200
        st, _ = _signed_request(b.spec.port, "PUT", "/obsthr/obj",
                                body=b"x")
        assert st == 200
        t.join(timeout=20)
        assert got and got[0]["node"] == b.spec.addr
        _await_no_pumps()
        # /events?follow=1 parity on the same threaded cluster: the
        # journal stream must hold across frontends too (ISSUE 18)
        from minio_tpu.utils import eventlog
        ev: list = []
        t2 = threading.Thread(
            target=lambda: ev.extend(
                _mc(a).events_follow(count=1, classes="net.heal",
                                     timeout=60)),
            daemon=True)
        t2.start()
        deadline = time.monotonic() + 10
        while not _event_pumps() and time.monotonic() < deadline:
            time.sleep(0.1)
        _drive_event_until(
            t2, lambda: eventlog.emit("net.heal", peers="thr|parity"))
        assert ev and ev[0]["class"] == "net.heal"
        _await_no_event_pumps()
    finally:
        for n in nodes:
            n.shutdown()


# ---------------------------------------------------------------------------
# 2b. live journal streaming — /events?follow=1 (ISSUE 18)
# ---------------------------------------------------------------------------

def _event_pumps() -> list:
    return [t for t in threading.enumerate()
            if t.name == "event-follow-peer" and t.is_alive()]


def _await_no_event_pumps(deadline_s: float = 12.0) -> None:
    deadline = time.monotonic() + deadline_s
    while _event_pumps() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert not _event_pumps(), (
        "peer event subscriptions leaked pump threads: "
        + ", ".join(t.name for t in _event_pumps()))


def _drive_event_until(thread, emit_fn, deadline_s: float = 15.0):
    """Emit on a cadence until the follow consumer finishes — the
    stream's peer grafts subscribe asynchronously, so a single emit
    can race the subscription window."""
    deadline = time.monotonic() + deadline_s
    while thread.is_alive() and time.monotonic() < deadline:
        emit_fn()
        thread.join(timeout=0.3)
    assert not thread.is_alive(), "events follow never delivered"


def test_events_follow_delivers_and_unwinds(cluster):
    """A /events?follow=1 stream on node A delivers a journal event,
    grafts peer subscriptions (the pump threads exist while open), and
    ends at count without leaking them."""
    from minio_tpu.utils import eventlog
    a, _b = cluster
    got: list = []
    t = threading.Thread(
        target=lambda: got.extend(
            _mc(a).events_follow(count=1, classes="net.heal",
                                 timeout=60)),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while not _event_pumps() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _event_pumps(), "peer event subscription never opened"
    _drive_event_until(
        t, lambda: eventlog.emit("net.heal", peers="obs|follow"))
    assert got and got[0]["class"] == "net.heal"
    assert got[0]["attrs"]["peers"] == "obs|follow"
    assert got[0]["sub"] == "net" and "seq" in got[0]
    _await_no_event_pumps()


def test_events_follow_disconnect_frees_workers(cluster):
    """A client that vanishes mid-/events-follow must unwind the
    server-side generator (heartbeat write fails -> peer pumps exit) —
    the PR-12 trace-stream lesson applied to the journal stream."""
    a, _b = cluster
    path = "/minio/admin/v3/events"
    query = {"follow": ["1"]}
    qs = urllib.parse.urlencode({"follow": "1"})
    hdrs = sig.sign_v4("GET", path, query,
                       {"host": f"127.0.0.1:{a.spec.port}"},
                       hashlib.sha256(b"").hexdigest(), CREDS, REGION)
    s = socket.create_connection(("127.0.0.1", a.spec.port),
                                 timeout=10)
    head = f"GET {path}?{qs} HTTP/1.1\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in hdrs.items()) + "\r\n"
    s.sendall(head.encode())
    buf = s.recv(4096)                 # headers (+ maybe a heartbeat)
    assert b"200" in buf.split(b"\r\n", 1)[0]
    deadline = time.monotonic() + 10
    while not _event_pumps() and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _event_pumps(), "peer event subscription never opened"
    s.close()                          # client dies
    _await_no_event_pumps()


def test_events_endpoint_filters_and_cluster_merge(cluster):
    """The non-follow /events window: class/severity filters apply,
    and ?cluster=1 merges peer windows WITHOUT duplicating entries —
    in-process nodes share one journal, so the merge must dedupe by
    (node, seq)."""
    from minio_tpu.utils import eventlog
    a, _b = cluster
    eventlog.emit("drive.suspect", drive="/obs/d9", set=3)
    ents = _mc(a).events(classes="drive.suspect")
    assert any(e["attrs"].get("drive") == "/obs/d9" for e in ents)
    assert all(e["class"] == "drive.suspect" for e in ents)
    for e in _mc(a).events(severity="error"):
        assert e["sev"] in ("error", "crit"), e
    merged = _mc(a).events(cluster=True, classes="drive.suspect")
    keys = [(e["node"], e["seq"]) for e in merged]
    assert len(keys) == len(set(keys)), "cluster merge duplicated"
    assert any(e["attrs"].get("drive") == "/obs/d9" for e in merged)


def test_drivehealth_surfaces_journal(cluster):
    """Satellite (a): the drivehealth document carries the
    journal-backed transition history next to the in-memory deque."""
    from minio_tpu.utils import eventlog
    a, _b = cluster
    eventlog.emit("drive.probation", drive="/obs/dh", set=1)
    doc = _mc(a).drive_health()
    j = doc.get("journal")
    assert isinstance(j, list)
    assert any(e["class"] == "drive.probation"
               and e["attrs"].get("drive") == "/obs/dh" for e in j)
    assert all(e["sub"] in ("drive", "health") for e in j)


def test_slo_endpoint_reports_objectives(cluster):
    """GET /slo answers with the burn-rate status document."""
    a, _b = cluster
    doc = _mc(a).slo()
    assert "objectives" in doc and "burn_threshold" in doc
    names = {o["objective"] for o in doc["objectives"]}
    assert {"read-availability", "write-availability",
            "read-latency", "write-latency"} <= names


# ---------------------------------------------------------------------------
# single-server surfaces: shed reason, spans filters, edge parity,
# loop lag, stage split
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def layer(tmp_path_factory):
    root = tmp_path_factory.mktemp("obsdrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    yield sets
    sets.close()


def _mk_server(layer, **env) -> S3Server:
    import os
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        srv = S3Server(layer, creds=CREDS, region=REGION).start()
        mount_admin(srv)
        return srv
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_shed_reason_rides_trace_records(layer):
    """A 503 shed's trace record carries WHY (the admission reason
    label) — on the staging-window path through the middleware."""
    srv = _mk_server(layer, MINIO_TPU_EDGE="on")
    try:
        srv.api.admission._shed_until = time.monotonic() + 30.0
        try:
            st, _ = _signed_request(srv.port, "PUT", "/shedtr/obj",
                                    body=b"x" * 64)
            assert st == 503
        finally:
            srv.api.admission._shed_until = 0.0
        entries = [e for e in srv.api.trace.recent
                   if e.get("status") == 503
                   and e.get("path") == "/shedtr/obj"]
        assert entries and entries[-1]["shed_reason"] == "staging", \
            entries[-2:]
    finally:
        srv.stop()


def test_spans_endpoint_filters(layer):
    srv = _mk_server(layer, MINIO_TPU_EDGE="on")
    was = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    telemetry.SPANS.configure(sample=1.0)
    try:
        assert _signed_request(srv.port, "PUT", "/spfil")[0] == 200
        assert _signed_request(srv.port, "PUT", "/spfil/obj",
                               body=b"z" * 4096)[0] == 200
        assert _signed_request(srv.port, "GET", "/spfil/obj")[0] == 200
        st, body = _signed_request(srv.port, "GET",
                                   "/minio/admin/v3/spans",
                                   {"api": "PutObject",
                                    "count": "100"})
        assert st == 200
        spans = json.loads(body)["spans"]
        assert spans and all(s["name"] == "PutObject" for s in spans)
        tid = spans[0]["trace_id"]
        st, body = _signed_request(srv.port, "GET",
                                   "/minio/admin/v3/spans",
                                   {"trace_id": tid})
        picked = json.loads(body)["spans"]
        assert len(picked) == 1 and picked[0]["trace_id"] == tid
    finally:
        telemetry.SPANS.configure(*was)
        srv.stop()


def _find(node: dict, name: str) -> list:
    out = [node] if node["name"] == name else []
    for c in node.get("children", ()):
        out.extend(_find(c, name))
    return out


def test_edge_trace_parity_with_threaded_oracle(layer):
    """An edge-served request roots the SAME span tree shape as the
    threaded oracle: same root name and attrs, engine child present,
    TTFB recorded (trace entry + histogram family) — satellite 2's
    parity pin."""
    from minio_tpu.s3.edge import dispatch as edge_dispatch
    was = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    telemetry.SPANS.configure(sample=1.0)
    roots: dict = {}
    entries: dict = {}
    ttfb_delta: dict = {}
    try:
        for tag, env in (("edge", "on"), ("threaded", "off")):
            srv = _mk_server(layer, MINIO_TPU_EDGE=env)
            try:
                assert srv.edge_enabled == (env == "on")
                path = f"/part-{tag}/obj"
                before = edge_dispatch._HTTP_TTFB.count(
                    api="PutObject")
                assert _signed_request(srv.port, "PUT",
                                       f"/part-{tag}")[0] == 200
                assert _signed_request(srv.port, "PUT", path,
                                       body=b"p" * 100000)[0] == 200
                # the client sees the response a hair before the
                # server thread reaches the histogram observes in
                # run_request's finally — poll, don't read instantly
                hist_deadline = time.monotonic() + 5.0
                while time.monotonic() < hist_deadline:
                    ttfb_delta[tag] = edge_dispatch._HTTP_TTFB.count(
                        api="PutObject") - before
                    if ttfb_delta[tag]:
                        break
                    time.sleep(0.01)
                # the client sees the response a hair before the
                # server closes (and offers) the root span — poll
                trees: list = []
                deadline = time.monotonic() + 5.0
                while not trees and time.monotonic() < deadline:
                    trees = [t for t in telemetry.SPANS.dump(200)
                             if t["name"] == "PutObject"
                             and t.get("attrs", {}).get("path") == path]
                    if not trees:
                        time.sleep(0.05)
                assert trees, f"no kept PutObject tree for {tag}"
                roots[tag] = trees[-1]
                ent = [e for e in srv.api.trace.recent
                       if e.get("path") == path
                       and e.get("api") == "PutObject"]
                assert ent
                entries[tag] = ent[-1]
            finally:
                srv.stop()
    finally:
        telemetry.SPANS.configure(*was)
    e, t = roots["edge"], roots["threaded"]
    # same root identity: name + attr KEYS + method attr value
    assert e["name"] == t["name"] == "PutObject"
    assert set(e.get("attrs", {})) == set(t.get("attrs", {}))
    assert e["attrs"]["method"] == t["attrs"]["method"] == "PUT"
    # same tree shape where it matters: the engine child roots below
    # the handler on both transports
    assert _find(e, "engine.put_object") and \
        _find(t, "engine.put_object")
    # TTFB recorded on both: per-request histogram sample + entry field
    assert ttfb_delta == {"edge": 1, "threaded": 1}
    assert entries["edge"].get("ttfb_ms", 0) > 0
    assert entries["threaded"].get("ttfb_ms", 0) > 0


def test_edge_loop_lag_and_pool_gauges(layer):
    """The edge's own observability: the per-loop lag sampler observes
    ticks and the worker-pool busy/idle gauges render at exposition
    time."""
    srv = _mk_server(layer, MINIO_TPU_EDGE="on",
                     MINIO_TPU_EDGE_LAG_S="0.05")
    try:
        # a request spins up a pool worker so the gauges have a pool
        assert _signed_request(
            srv.port, "GET", "/minio/prometheus/metrics")[0] == 200
        time.sleep(0.5)                # a few sampler ticks
        st, body = _signed_request(srv.port, "GET",
                                   "/minio/prometheus/metrics")
        assert st == 200
        text = body.decode()
        m = re.search(
            r'minio_tpu_edge_loop_lag_seconds_count\{loop="0"\} (\d+)',
            text)
        assert m and int(m.group(1)) >= 3, \
            "lag sampler never ticked"
        for fam in ("minio_tpu_edge_pool_size",
                    "minio_tpu_edge_pool_busy",
                    "minio_tpu_edge_pool_idle",
                    "minio_tpu_edge_pool_pending",
                    "minio_tpu_edge_open_conns"):
            assert f"\n{fam} " in text or f"\n{fam}{{" in text, fam
    finally:
        srv.stop()


def test_promfed_label_escape_roundtrip():
    """Label values survive the merge's escape/unescape — sequential
    .replace() corrupted backslash-bearing values ('\\\\' + 'n' read
    back as a newline; review finding)."""
    for v in ("C:\\d1\\new", 'quo"te', "multi\nline", "\\n", "plain"):
        assert promfed._unescape(promfed._escape(v)) == v, v
    merged = promfed.merge_expositions(
        [("n1", '# TYPE g gauge\ng{path="C:\\\\d1\\\\new"} 1\n')])
    fams = promfed.parse_exposition(merged)
    assert ("g", (("node", "n1"), ("path", "C:\\d1\\new"))) \
        in fams["g"].samples


def test_filtered_nonfollow_stream_idles_out_on_matches():
    """A filtered non-follow stream on a server with steady
    NON-matching traffic must still terminate at idle_timeout: idle
    counts from the last MATCHED entry, else the worker + hub
    subscription leak forever (review finding)."""
    from minio_tpu.s3.trace import TraceSys
    ts = TraceSys(node_name="n1")
    stop = threading.Event()

    def spam():
        while not stop.is_set():
            ts.record("GET", "/b/k", "", 200, 0.001, api="GetObject")
            time.sleep(0.05)

    t = threading.Thread(target=spam, daemon=True)
    t.start()
    try:
        t0 = time.monotonic()
        out = list(ts.stream(idle_timeout=0.5, apis={"PutObject"}))
        dt = time.monotonic() - t0
        assert out == []
        assert dt < 5.0, f"filtered stream never idled out ({dt:.1f}s)"
    finally:
        stop.set()
        t.join(timeout=2)


# ---------------------------------------------------------------------------
# 3. dispatch-stage attribution
# ---------------------------------------------------------------------------

def test_dispatch_stage_split_and_exposition_lint(monkeypatch):
    """A fused dispatch records a nonzero queue/transfer/compute stage
    split (histogram + child spans under sched.dispatch) and the
    family renders as a lint-clean histogram triplet."""
    import numpy as np
    from minio_tpu import bitrot
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.parallel.scheduler import BatchScheduler

    monkeypatch.setattr(codec_mod, "_IS_TPU", True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    hist = telemetry.REGISTRY.histogram(
        "minio_tpu_device_dispatch_seconds")
    before = {s: hist.count(verb="encode", stage=s)
              for s in ("queue", "transfer", "compute", "fetch")}
    sched = BatchScheduler(max_wait=0.05)
    codec = codec_mod.Codec(4, 2, 4 * 4096)
    data = np.random.randint(0, 255, (4, 4, 4096), dtype=np.uint8)
    try:
        with telemetry.trace("obs-stage-test") as root:
            out = sched.submit(
                codec, data,
                bitrot.BitrotAlgorithm.HIGHWAYHASH256).result(120)
        assert out is not None, "dispatch declined the device route"
    finally:
        sched.close()
    # nonzero queue + compute split (transfer can round to ~0 on a
    # single-group batch but must be OBSERVED; fetch merges into
    # compute on the mesh path)
    for s in ("queue", "transfer", "compute"):
        assert hist.count(verb="encode", stage=s) > before[s], s
    # the dispatch span carries the stage children
    tree = root.to_dict()
    d = _find(tree, "sched.dispatch")
    assert d, tree
    child_names = {c["name"] for c in d[0].get("children", ())}
    assert {"sched.queue", "sched.compute"} <= child_names, child_names
    # exposition lint: histogram triplet with consistent labels
    text = telemetry.REGISTRY.render()
    fam = "minio_tpu_device_dispatch_seconds"
    assert f"# TYPE {fam} histogram" in text
    assert re.search(
        fam + r'_bucket\{stage="compute",verb="encode",le="[^"]+"\}',
        text)
    assert f"{fam}_sum{{" in text and f"{fam}_count{{" in text
    # inflight gauge registered and rendered
    assert "minio_tpu_sched_inflight_dispatches" in text


# ---------------------------------------------------------------------------
# killed peer — LAST: tears down node B of the shared cluster
# ---------------------------------------------------------------------------

def test_killed_peer_degrades_scrape_not_fails(cluster):
    """Kill node B for real: node A's ?cluster=1 scrape still answers
    (node A's families present) and the failure is counted per node in
    minio_tpu_cluster_scrape_failed_total."""
    a, b = cluster
    b_addr = b.spec.addr
    shed = telemetry.REGISTRY.counter(
        "minio_tpu_cluster_scrape_failed_total")
    before = shed.value(node=b_addr)
    b.shutdown()
    merged = _mc(a).cluster_metrics()
    assert shed.value(node=b_addr) >= before + 1
    fams = promfed.parse_exposition(merged)
    assert "minio_tpu_http_requests_duration_seconds" in fams
    assert fams["minio_tpu_cluster_scrape_failed_total"].samples[
        ("minio_tpu_cluster_scrape_failed_total",
         (("node", b_addr),))] >= 1
