"""In-process crash-consistency tests: the fsck auditor per finding
class, torn-write injection through the crashpoint hook, torn
checkpoint/registry tolerance (satellite bugfix sweep), commit-window
abort semantics (previous version stays readable), and the metacache
persist-crash fallback — the tier-1 half of the crash plane (the
subprocess SIGKILL matrix lives in tests/test_crash.py, slow)."""

from __future__ import annotations

import json
import os
import time

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.background import MRFHealer
from minio_tpu.object.engine import PutOptions
from minio_tpu.object.fsck import run_fsck
from minio_tpu.object.metacache import MetacacheManager, manifest_key, \
    mc_prefix
from minio_tpu.object.rebalance import Rebalancer
from minio_tpu.object.rebalance import _checkpoint_object as reb_ckpt
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.topology import TopologyStore
from minio_tpu.replicate.resync import Resyncer
from minio_tpu.storage.xl_storage import MINIO_META_BUCKET
from minio_tpu.utils import atomicfile, crashpoint

K, M, NDISKS = 4, 2, 6
BLOCK = 1 << 16
ORIGIN_KEY = "X-Minio-Internal-replication-origin"


def make_zones(tmp_path, pools=1, tag="p"):
    zz = ErasureServerSets(
        [ErasureSets.from_drives(
            [str(tmp_path / f"{tag}{p}d{j}") for j in range(NDISKS)],
            1, NDISKS, M, block_size=BLOCK, enable_mrf=False)
         for p in range(pools)],
        load_topology=False)
    zz.make_bucket("b")
    return zz


@pytest.fixture()
def zz(tmp_path):
    z = make_zones(tmp_path)
    yield z
    z.close()


@pytest.fixture(autouse=True)
def _disarm():
    crashpoint.disarm()
    yield
    crashpoint.disarm()


def eng_of(zz, pool=0):
    return zz.server_sets[pool].sets[0]


def get_bytes(zz, bucket, name):
    _info, stream = zz.get_object(bucket, name)
    try:
        return b"".join(stream)
    finally:
        close = getattr(stream, "close", None)
        if close:
            close()


# ---------------------------------------------------------------------------
# fsck per finding class
# ---------------------------------------------------------------------------

def test_fsck_clean_tree(zz):
    zz.put_object("b", "ok", b"x" * 1000)
    rep = run_fsck(zz, tmp_age_s=0)
    assert rep.clean and rep.supported
    assert rep.objects_scanned >= 1


def test_fsck_orphan_data_dir(zz):
    zz.put_object("b", "obj", b"x" * 1000)
    d0 = eng_of(zz).disks[0]
    orphan = os.path.join(d0.root, "b", "obj", "11111111-dead")
    os.makedirs(orphan)
    with open(os.path.join(orphan, "part.1"), "wb") as f:
        f.write(b"junk")
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"orphan_data": 1}
    assert rep.repaired_counts() == {"orphan_data": 1}
    assert not os.path.exists(orphan)
    # the committed copy is untouched
    assert get_bytes(zz, "b", "obj") == b"x" * 1000
    assert run_fsck(zz, tmp_age_s=0).clean


def test_fsck_tmp_age_gate(zz):
    d0 = eng_of(zz).disks[0]
    stale = os.path.join(d0.root, ".minio.sys", "tmp", "stale-uuid")
    os.makedirs(stale)
    with open(os.path.join(stale, "f"), "wb") as f:
        f.write(b"junk")
    # a FRESH staged dir is NOT reaped under the default age gate (it
    # could be an in-flight PUT)…
    assert run_fsck(zz).counts() == {}
    # …but the explicit quiesced mode reaps it
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"stale_tmp": 1}
    assert not os.path.exists(stale)


def test_fsck_meta_missing_heals(zz):
    zz.put_object("b", "deg", b"y" * 800)
    eng = eng_of(zz)
    os.unlink(os.path.join(eng.disks[1].root, "b", "deg", "xl.meta"))
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"meta_missing": 1}
    assert rep.repaired_counts() == {"meta_missing": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    assert os.path.exists(
        os.path.join(eng.disks[1].root, "b", "deg", "xl.meta"))


def test_fsck_missing_shards_heal_and_lost(zz):
    import shutil
    zz.put_object("b", "sh", b"z" * 4000)
    eng = eng_of(zz)
    fi = eng.disks[0].read_versions("b", "sh")[0]
    # drop the data dir on ONE drive (≤ parity): repairable
    shutil.rmtree(os.path.join(eng.disks[2].root, "b", "sh",
                               fi.data_dir))
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"missing_shards": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    # drop it below the decode quorum: LOST, reported, not repairable
    for j in range(NDISKS - K + 1):
        p = os.path.join(eng.disks[j].root, "b", "sh", fi.data_dir)
        if os.path.isdir(p):
            shutil.rmtree(p)
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert "lost_data" in rep.counts()
    lost = [f for f in rep.findings if f.cls == "lost_data"]
    assert lost and not lost[0].repairable


def test_fsck_origin_divergence_repairs(zz):
    zz.put_object("b", "repl", b"r" * 600,
                  opts=PutOptions(versioned=True))
    eng = eng_of(zz)
    for j, site in ((0, "site-A"), (1, "site-B")):
        fi = eng.disks[j].read_versions("b", "repl")[0]
        fi.metadata[ORIGIN_KEY] = site
        eng.disks[j].write_metadata("b", "repl", fi)
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"origin_divergence": 1}
    assert rep.repaired_counts() == {"origin_divergence": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    origins = {d.read_versions("b", "repl")[0].metadata.get(ORIGIN_KEY)
               for d in eng.disks}
    assert len(origins) == 1


def test_fsck_stale_multipart(zz):
    eng = eng_of(zz)
    # session dir with NO readable session meta on any drive (a torn
    # new_multipart_upload)
    for d in eng.disks:
        p = os.path.join(d.root, ".minio.sys", "multipart", "shaX",
                         "upl1", "dd")
        os.makedirs(p)
        with open(os.path.join(p, "part.1"), "wb") as f:
            f.write(b"junk")
    # a LIVE session must be untouched
    up = zz.new_multipart_upload("b", "live-mpu")
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"stale_multipart": 1}
    assert not os.path.exists(os.path.join(
        eng.disks[0].root, ".minio.sys", "multipart", "shaX"))
    # live session still works end-to-end
    from minio_tpu.object import CompletePart
    pi = zz.put_object_part("b", "live-mpu", up, 1, b"m" * 700)
    zz.complete_multipart_upload("b", "live-mpu", up,
                                 [CompletePart(1, pi.etag)])
    assert get_bytes(zz, "b", "live-mpu") == b"m" * 700


def test_fsck_torn_registry_rewrites_from_best_copy(tmp_path):
    zz = make_zones(tmp_path, pools=2)
    try:
        epoch = zz.set_pool_state(1, "suspended")   # persist a real doc
        zz.set_pool_state(1, "active")
        # tear pool 0's copy only
        zz.server_sets[0].put_object(MINIO_META_BUCKET,
                                     "topology/pools.json", b'{"epo')
        rep = run_fsck(zz, repair=True, tmp_age_s=0)
        assert rep.counts() == {"torn_registry": 1}
        assert rep.repaired_counts() == {"torn_registry": 1}
        assert run_fsck(zz, tmp_age_s=0).clean
        # the rewritten copy parses and carries the good epoch
        loaded = TopologyStore.load(zz)
        assert loaded is not None and loaded.epoch >= epoch
    finally:
        zz.close()


def test_fsck_torn_registry_single_copy_drops(zz):
    zz.server_sets[0].put_object(MINIO_META_BUCKET,
                                 "replicate/targets.json", b"\x00garb")
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"torn_registry": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    with pytest.raises(api_errors.ObjectApiError):
        zz.get_object(MINIO_META_BUCKET, "replicate/targets.json")


def test_fsck_dangling_stub(tmp_path, zz):
    from minio_tpu.tier.config import TierConfig, TierManager
    tiers = TierManager(zz)
    tiers.add(TierConfig.from_dict(
        {"name": "t1", "type": "fs",
         "params": {"path": str(tmp_path / "tier")}}))
    zz.put_object("b", "cold", b"cold" * 300)
    client = tiers.client("t1")
    import io as _io
    client.put("rk1", _io.BytesIO(b"cold" * 300), 1200)
    zz.transition_object("b", "cold", tier="t1", remote_object="rk1")
    # intact stub: clean
    assert run_fsck(zz, tiers=tiers, tmp_age_s=0).clean
    client.delete("rk1")                      # remote copy vanishes
    rep = run_fsck(zz, repair=True, tiers=tiers, tmp_age_s=0)
    assert rep.counts() == {"dangling_stub": 1}
    assert rep.repaired_counts() == {"dangling_stub": 1}
    with pytest.raises(api_errors.ObjectApiError):
        zz.get_object_info("b", "cold")
    assert run_fsck(zz, tiers=tiers, tmp_age_s=0).clean


def test_fsck_metacache_orphan_segment_and_broken_manifest(zz):
    # orphan segment: a seg object no manifest references
    zz.put_object(MINIO_META_BUCKET, mc_prefix("b") + "seg-dead.json",
                  b"[]")
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"orphan_metacache_segment": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    # manifest referencing a missing segment: dropped whole
    zz.put_object(MINIO_META_BUCKET, manifest_key("b"), json.dumps(
        {"format": 1, "bucket": "b", "gen": 3,
         "segments": [{"key": mc_prefix("b") + "seg-gone.json",
                       "first": "", "count": 0}]}).encode())
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"broken_metacache_manifest": 1}
    assert run_fsck(zz, tmp_age_s=0).clean
    with pytest.raises(api_errors.ObjectApiError):
        zz.get_object(MINIO_META_BUCKET, manifest_key("b"))


def test_fsck_fs_backend_unsupported(tmp_path):
    from minio_tpu.object.fs import FSObjects
    rep = run_fsck(FSObjects(str(tmp_path / "fs")))
    assert not rep.supported and rep.clean


def test_fsck_metrics_count_per_class(zz):
    from minio_tpu.utils import telemetry
    fam = telemetry.REGISTRY.counter("minio_tpu_fsck_findings_total")
    zz.put_object("b", "obj", b"x" * 400)
    eng = eng_of(zz)
    os.unlink(os.path.join(eng.disks[0].root, "b", "obj", "xl.meta"))
    before = dict(getattr(fam, "_values", {}))
    run_fsck(zz, repair=True, tmp_age_s=0)
    text = telemetry.REGISTRY.render()
    assert 'minio_tpu_fsck_findings_total{class="meta_missing"}' in text
    assert 'minio_tpu_fsck_repaired_total{class="meta_missing"}' in text
    assert before is not None   # smoke: family existed before the run


# ---------------------------------------------------------------------------
# commit-window aborts: previous version stays readable (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("point", ["put.shards.before_meta",
                                   "put.meta.before_rename"])
def test_crash_between_fanout_and_commit_keeps_previous(zz, point):
    zz.put_object("b", "obj", b"OLD" * 500)
    crashpoint.arm(point)
    with pytest.raises(crashpoint.CrashpointAbort):
        zz.put_object("b", "obj", b"NEW" * 700)
    crashpoint.disarm()
    assert get_bytes(zz, "b", "obj") == b"OLD" * 500
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert len(rep.unrepaired) == 0
    assert run_fsck(zz, tmp_age_s=0).clean
    assert get_bytes(zz, "b", "obj") == b"OLD" * 500


def test_partial_rename_degrades_not_tears(zz):
    """One drive's rename aborted mid-fan-out: the commit still meets
    quorum, the object reads back complete, and fsck+heal restore full
    redundancy."""
    crashpoint.arm("put.rename.partial", nth=1)
    zz.put_object("b", "part", b"P" * 3000)     # succeeds degraded
    crashpoint.disarm()
    assert get_bytes(zz, "b", "part") == b"P" * 3000
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert len(rep.unrepaired) == 0
    assert run_fsck(zz, tmp_age_s=0).clean
    assert get_bytes(zz, "b", "part") == b"P" * 3000


# ---------------------------------------------------------------------------
# torn-write injection (storage.write_all.commit)
# ---------------------------------------------------------------------------

def test_torn_write_injection(tmp_path):
    """The crashpoint hook doubles as the torn-write injector: the
    armed action commits a TRUNCATED copy under the final name before
    aborting — the on-disk state a power cut without fsync discipline
    leaves — and the tolerant doc loader reads it as absent."""
    from minio_tpu.storage.xl_storage import XLStorage
    d = XLStorage(str(tmp_path / "drv"))
    d.make_vol_bulk("vol")
    doc = json.dumps({"epoch": 12, "pools": ["active"]}).encode()
    crashpoint.arm("storage.write_all.commit",
                   action=crashpoint.torn_write_action(0.5))
    with pytest.raises(crashpoint.CrashpointAbort):
        d.write_all("vol", "doc.json", doc)
    crashpoint.disarm()
    torn = d.read_all("vol", "doc.json")
    assert 0 < len(torn) < len(doc)
    assert atomicfile.load_json_doc(torn) is None
    # a clean rewrite replaces the torn copy atomically
    d.write_all("vol", "doc.json", doc)
    assert atomicfile.load_json_doc(d.read_all("vol", "doc.json")) \
        == json.loads(doc)


def test_torn_staged_meta_on_one_drive_converges(zz):
    """Tear ONE drive's staged xl.meta mid-PUT: quorum still commits,
    the object reads back complete, and fsck reclaims the leaked tmp
    staging the torn drive left behind."""
    crashpoint.arm("storage.write_all.commit",
                   action=crashpoint.torn_write_action(0.3))
    zz.put_object("b", "torn", b"T" * 2500)
    crashpoint.disarm()
    assert get_bytes(zz, "b", "torn") == b"T" * 2500
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert len(rep.unrepaired) == 0
    assert run_fsck(zz, tmp_age_s=0).clean
    assert get_bytes(zz, "b", "torn") == b"T" * 2500


# ---------------------------------------------------------------------------
# MRF drain crash (in-process: crash loses only the retry)
# ---------------------------------------------------------------------------

def test_mrf_drain_crash():
    healed = []
    mrf = MRFHealer(lambda b, o, v: healed.append((b, o, v)),
                    backoff_base=0.01, backoff_max=0.05)
    try:
        crashpoint.arm("mrf.drain.before_heal")
        assert mrf.enqueue("b", "o", "v")
        deadline = time.monotonic() + 5
        while crashpoint.hits("mrf.drain.before_heal") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert crashpoint.hits("mrf.drain.before_heal") >= 1
        # the aborted drain requeued the entry instead of losing it
        assert mrf.drain(timeout=5)
        assert healed == [("b", "o", "v")]
        assert mrf.requeued >= 1 and mrf.healed == 1
    finally:
        crashpoint.disarm()
        mrf.close()


# ---------------------------------------------------------------------------
# torn checkpoint/registry loaders (satellite bugfix sweep)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("payload", [b'{"updated": 5, "mark',  # torn
                                     b"12",      # valid-JSON wrong type
                                     b"",        # empty file
                                     b"\xff\xfe garbage"])
def test_rebalance_checkpoint_torn_tolerated(tmp_path, payload):
    zz = make_zones(tmp_path, pools=2)
    try:
        zz.server_sets[0].put_object(MINIO_META_BUCKET, reb_ckpt(1),
                                     payload)
        assert Rebalancer.load_checkpoint(zz, 1) is None
        # a GOOD copy on another pool still wins
        zz.server_sets[1].put_object(
            MINIO_META_BUCKET, reb_ckpt(1),
            json.dumps({"updated": 9.0, "bucket": "b",
                        "marker": "k"}).encode())
        doc = Rebalancer.load_checkpoint(zz, 1)
        assert doc and doc["marker"] == "k"
        # resume with only the torn copy must not crash boot
        zz.server_sets[0].put_object(MINIO_META_BUCKET, reb_ckpt(1),
                                     payload)
        zz.server_sets[1].delete_object(MINIO_META_BUCKET, reb_ckpt(1))
        assert Rebalancer.load_checkpoint(zz, 1) is None
        assert zz.resume_rebalance_if_pending() is False
    finally:
        zz.close()


def test_resync_checkpoint_torn_tolerated(zz):
    from minio_tpu.replicate.resync import _checkpoint_object
    arn = "arn:minio:repl:site:x"
    zz.put_object(MINIO_META_BUCKET, _checkpoint_object(arn), b'{"to')
    assert Resyncer.load_checkpoint(zz, arn) is None


def test_registry_loads_tolerate_torn_copy(tmp_path):
    zz = make_zones(tmp_path, pools=2)
    try:
        epoch = zz.set_pool_state(1, "suspended")
        zz.server_sets[0].put_object(MINIO_META_BUCKET,
                                     "topology/pools.json", b"[1, 2")
        loaded = TopologyStore.load(zz)
        assert loaded is not None and loaded.epoch == epoch
        # both copies torn: load reports nothing, boot defaults apply
        zz.server_sets[1].put_object(MINIO_META_BUCKET,
                                     "topology/pools.json", b"[1, 2")
        assert TopologyStore.load(zz) is None
        fresh = ErasureServerSets(zz.server_sets)   # boots all-active
        assert fresh.topology.write_pools() == [0, 1]
    finally:
        zz.close()


def test_tier_and_target_registry_tolerate_torn_docs(zz):
    from minio_tpu.replicate.targets import TargetRegistry
    from minio_tpu.tier.config import TierManager
    zz.put_object(MINIO_META_BUCKET, "tier/config.json", b'{"epoch"')
    zz.put_object(MINIO_META_BUCKET, "replicate/targets.json", b"7")
    assert TierManager(zz).load() is False
    reg = TargetRegistry(zz)
    assert reg.load() is False


# ---------------------------------------------------------------------------
# metacache persist crash: fallback + rebuild, never a half manifest
# ---------------------------------------------------------------------------

def _attach(zz, **kw):
    kw.setdefault("staleness_s", 0.0)
    kw.setdefault("flush_s", 0.05)
    mgr = MetacacheManager(zz, **kw)
    mgr.start()
    zz.attach_metacache(mgr)
    return mgr


def _oracle(zz, bucket="b"):
    mc, zz.metacache = zz.metacache, None
    try:
        objs, _p, _t = zz.list_objects(bucket, "", "", "", 1000)
        return [o.name for o in objs]
    finally:
        zz.metacache = mc


def test_metacache_persist_crash_falls_back_and_rebuilds(zz):
    """Crash between segment writes and the manifest write: the next
    manager start finds no (or a prior) manifest, walk-rebuilds, and
    serves pages equal to the merge-walk oracle; fsck reclaims the
    orphaned segments the dead attempt left."""
    for i in range(8):
        zz.put_object("b", f"k{i:02d}", bytes([i]) * 300)
    mgr = _attach(zz, persist_s=0.0)
    crashpoint.arm("metacache.persist.before_manifest")
    try:
        deadline = time.monotonic() + 10
        while mgr.persist_errors == 0 and time.monotonic() < deadline:
            zz.list_objects("b", "", "", "", 100)   # build + serve
            time.sleep(0.05)
        assert mgr.persist_errors >= 1, "persist crash never fired"
    finally:
        crashpoint.disarm()
    # live serving survived the failed persist
    objs, _p, _t = zz.list_objects("b", "", "", "", 100)
    assert [o.name for o in objs] == _oracle(zz)
    mgr.close(flush=False)
    zz.metacache = None

    # "restart": a fresh manager finds segments without a manifest —
    # it must walk-rebuild, never serve the half-written state
    mgr2 = _attach(zz)
    objs, _p, _t = zz.list_objects("b", "", "", "", 100)
    assert [o.name for o in objs] == _oracle(zz)
    mgr2.close(flush=False)
    zz.metacache = None

    # the IN-PROCESS abort runs _persist's failure path, which
    # reclaims the attempt's fresh segments itself (PR 7 discipline) —
    # so fsck finds a clean tree here; the true orphan-segment crash
    # state (hard exit skips the cleanup) is produced and repaired by
    # the subprocess matrix case for this same point
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert set(rep.counts()) <= {"orphan_metacache_segment"}
    assert len(rep.unrepaired) == 0
    assert run_fsck(zz, tmp_age_s=0).clean


def test_metacache_half_manifest_never_served(zz):
    """A manifest referencing segments that never landed (crash inside
    the segment fan-out of an earlier gen) must abandon the load and
    rebuild from the walk — pages stay oracle-identical."""
    def plant_half_manifest():
        zz.put_object(MINIO_META_BUCKET, manifest_key("b"), json.dumps(
            {"format": 1, "bucket": "b", "gen": 9,
             "segments": [{"key": mc_prefix("b") + "seg-never.json",
                           "first": "", "count": 5}]}).encode())

    for i in range(5):
        zz.put_object("b", f"m{i}", bytes([i + 1]) * 200)
    # restart-before-manager state: fsck must classify and drop it
    plant_half_manifest()
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert "broken_metacache_manifest" in rep.counts()
    assert run_fsck(zz, tmp_age_s=0).clean
    # a manager starting over the same state abandons the load and
    # walk-rebuilds (its first due persist then replaces the manifest
    # wholesale) — pages stay oracle-identical throughout
    plant_half_manifest()
    mgr = _attach(zz)
    try:
        objs, _p, _t = zz.list_objects("b", "", "", "", 100)
        assert [o.name for o in objs] == _oracle(zz)
    finally:
        mgr.close(flush=False)
        zz.metacache = None


# ---------------------------------------------------------------------------
# atomicfile
# ---------------------------------------------------------------------------

def test_write_atomic_and_fsync_knob(tmp_path, monkeypatch):
    p = str(tmp_path / "sub" / "doc.json")
    os.makedirs(os.path.dirname(p))
    atomicfile.write_atomic(p, b"hello")
    assert open(p, "rb").read() == b"hello"
    monkeypatch.setenv("MINIO_TPU_FSYNC", "on")
    assert atomicfile.fsync_enabled()
    atomicfile.write_atomic(p, b"world")     # barriers on: still atomic
    assert open(p, "rb").read() == b"world"
    assert not [f for f in os.listdir(os.path.dirname(p))
                if f.endswith(".tmp")]


def test_load_json_doc_shapes():
    assert atomicfile.load_json_doc(b'{"a": 1}') == {"a": 1}
    assert atomicfile.load_json_doc(b'{"a": 1') is None     # torn
    assert atomicfile.load_json_doc(b"12") is None          # wrong type
    assert atomicfile.load_json_doc(b"[1]") is None
    assert atomicfile.load_json_doc(b"") is None
    assert atomicfile.load_json_doc(b"\xff\x00") is None


# ---------------------------------------------------------------------------
# review-fix regressions
# ---------------------------------------------------------------------------

def test_fsck_reclaims_atomic_temp_siblings(zz):
    """A crash between write_atomic's temp write and its rename leaves
    `xl.meta.<hex>.tmp` INSIDE the object dir (not the tmp bucket) —
    fsck must reclaim it under the same age gate."""
    zz.put_object("b", "obj", b"x" * 600)
    d0 = eng_of(zz).disks[0]
    leftover = os.path.join(d0.root, "b", "obj", "xl.meta.ab12cd34.tmp")
    with open(leftover, "wb") as f:
        f.write(b'{"half')
    # fresh + default age gate: could be an in-flight commit — spared
    assert run_fsck(zz).counts() == {}
    rep = run_fsck(zz, repair=True, tmp_age_s=0)
    assert rep.counts() == {"stale_tmp": 1}
    assert not os.path.exists(leftover)
    assert get_bytes(zz, "b", "obj") == b"x" * 600
    assert run_fsck(zz, tmp_age_s=0).clean


def test_fsck_stub_spared_on_transient_tier_error(tmp_path, zz):
    """Only a POSITIVE TierObjectNotFound classifies a stub as
    dangling: an unreachable tier (network down at boot fsck) must
    never cause the irreversible stub drop."""
    from minio_tpu.tier.client import TierClientError
    from minio_tpu.tier.config import TierConfig, TierManager
    tiers = TierManager(zz)
    tiers.add(TierConfig.from_dict(
        {"name": "t1", "type": "fs",
         "params": {"path": str(tmp_path / "tier")}}))
    zz.put_object("b", "cold", b"c" * 900)
    import io as _io
    tiers.client("t1").put("rk", _io.BytesIO(b"c" * 900), 900)
    zz.transition_object("b", "cold", tier="t1", remote_object="rk")

    class DownClient:
        def head(self, key):
            raise TierClientError("connection refused")

    class DownTiers:
        def client(self, name):
            return DownClient()

    rep = run_fsck(zz, repair=True, tiers=DownTiers(), tmp_age_s=0)
    assert rep.counts() == {}           # cannot check != safe to drop
    # the stub is still there and restorable
    assert zz.get_object_info("b", "cold") is not None
    # an unmounted tier name is equally non-definitive
    class EmptyTiers:
        def client(self, name):
            raise KeyError(name)
    assert run_fsck(zz, repair=True, tiers=EmptyTiers(),
                    tmp_age_s=0).counts() == {}
