"""CI smoke for the bench.py --saturation sweep (2 tiny points): the
sweep must run end-to-end inside the tier-1 budget, emit
JSON-serializable results, and show the decode verb actually riding
the batch former with >1 group per dispatch once streams > 1."""

from __future__ import annotations

import json

import bench


def test_saturation_smoke_two_points():
    out = bench.bench_saturation(streams=(1, 2), size=2 << 16,
                                 drives=6, parity=2, block=1 << 16,
                                 ab=True, force_device=True,
                                 sched_max_wait=0.25)
    json.dumps(out)                       # BENCH-compatible payload
    assert out["config"]["forced_device_route"] is True
    assert [p["streams"] for p in out["points"]] == [1, 2]
    for p in out["points"]:
        for key in ("put_gib_s", "get_gib_s", "deg_get_gib_s"):
            assert p[key] >= 0
            assert p["bypass"][key] >= 0
        # degraded GETs exercised the decode verb on the former
        dec = p["sched_deg_get"]["decode"]
        assert dec["dispatches"] >= 1
    # with 2 concurrent streams the two requests' decode buckets share
    # dispatches: mean groups per dispatch must exceed 1
    dec2 = out["points"][1]["sched_deg_get"]["decode"]
    assert dec2["occupancy_groups"] > 1, dec2
