"""Durable event notification (VERDICT r2 item 3): at-least-once queue
store surviving restart (pkg/event/target/queuestore.go semantics) +
the new wire-protocol targets (Redis RESP2, MQTT 3.1.1, Kafka-shaped).
"""

from __future__ import annotations

import json
import socket
import threading
import time

import pytest

from minio_tpu.features.events import (EventNotifier, KafkaTarget,
                                       MemoryTarget, MQTTTarget,
                                       NotificationConfig, QueueStore,
                                       RedisTarget, event_record)


# ---------------------------------------------------------------------------
# queue store
# ---------------------------------------------------------------------------

def test_queuestore_roundtrip_and_limit(tmp_path):
    qs = QueueStore(str(tmp_path / "q"), limit=3)
    keys = [qs.put(event_record("s3:ObjectCreated:Put", "b", f"k{i}"))
            for i in range(3)]
    assert all(keys)
    assert qs.put(event_record("s3:ObjectCreated:Put", "b", "k3")) is None
    assert qs.keys() == sorted(keys)          # oldest first
    rec = qs.get(keys[0])
    assert rec["Records"][0]["s3"]["object"]["key"] == "k0"
    qs.delete(keys[0])
    assert len(qs.keys()) == 2


class _Meta:
    """bucket_meta stub: one bucket wired to one ARN for all events."""

    def __init__(self, arn):
        self.xml = (
            '<NotificationConfiguration>'
            '<QueueConfiguration>'
            f'<Queue>{arn}</Queue>'
            '<Event>s3:ObjectCreated:*</Event>'
            '</QueueConfiguration></NotificationConfiguration>')

    def get(self, bucket):
        class BM:
            notification_xml = self.xml
        return BM()


class FlakyTarget:
    """Fails until `ok` is set; then records deliveries."""

    def __init__(self, arn):
        self.arn = arn
        self.ok = False
        self.delivered: list[str] = []
        self._cond = threading.Condition()

    def send(self, record):
        with self._cond:
            if not self.ok:
                raise OSError("target down")
            self.delivered.append(
                record["Records"][0]["s3"]["object"]["key"])
            self._cond.notify_all()

    def wait_for(self, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.delivered) < n:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cond.wait(left):
                    return False
            return True


def test_events_survive_restart(tmp_path):
    """Events sent while the target is down must be delivered by a NEW
    notifier over the same queue dir — no event loss across restart."""
    arn = "arn:minio:sqs::1:webhook"
    meta = _Meta(arn)
    qdir = str(tmp_path / "events")

    n1 = EventNotifier(meta, retries=2, queue_dir=qdir,
                       redrive_interval=3600)
    down = FlakyTarget(arn)
    n1.register_target(down)
    for i in range(5):
        n1.send("s3:ObjectCreated:Put", "bkt", f"obj{i}")
    n1.drain(5)
    n1.close()                                 # "process dies"
    assert not down.delivered

    n2 = EventNotifier(meta, retries=2, queue_dir=qdir,
                       redrive_interval=3600)
    up = FlakyTarget(arn)
    up.ok = True
    n2.register_target(up)                     # startup replay
    assert up.wait_for(5), f"only {up.delivered} delivered"
    assert sorted(up.delivered) == [f"obj{i}" for i in range(5)]
    # store is empty after delivery: a third notifier delivers nothing
    n2.drain(5)
    n2.close()
    n3 = EventNotifier(meta, retries=2, queue_dir=qdir,
                       redrive_interval=3600)
    again = FlakyTarget(arn)
    again.ok = True
    n3.register_target(again)
    n3.drain(2)
    assert not again.delivered                 # no duplicates after ack
    n3.close()


def test_redrive_after_exhausted_retries(tmp_path):
    """Retries exhausted -> entry stays persisted; an explicit redrive
    (the periodic loop's body) delivers it once the target recovers."""
    arn = "arn:minio:sqs::1:webhook"
    meta = _Meta(arn)
    n = EventNotifier(meta, retries=2, queue_dir=str(tmp_path / "q"),
                      redrive_interval=3600)
    t = FlakyTarget(arn)
    n.register_target(t)
    n.send("s3:ObjectCreated:Put", "bkt", "late")
    n.drain(5)
    assert not t.delivered
    t.ok = True
    assert n.redrive() == 1
    assert t.wait_for(1)
    n.close()


# ---------------------------------------------------------------------------
# Redis target: real RESP2 against an in-process server
# ---------------------------------------------------------------------------

class FakeRedis:
    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.commands: list[list[bytes]] = []
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    while True:
                        line = f.readline()
                        if not line or line[:1] != b"*":
                            break
                        n = int(line[1:])
                        args = []
                        for _ in range(n):
                            ln = int(f.readline()[1:])
                            args.append(f.read(ln + 2)[:-2])
                        self.commands.append(args)
                        conn.sendall(b"+OK\r\n" if args[0] != b"RPUSH"
                                     else b":1\r\n")
                except Exception:
                    pass

    def close(self):
        self.sock.close()


def test_redis_target_namespace_and_access():
    srv = FakeRedis()
    try:
        t = RedisTarget("arn:minio:sqs::1:redis",
                        f"127.0.0.1:{srv.port}", "bucketevents")
        t.send(event_record("s3:ObjectCreated:Put", "b", "x/y"))
        t.send(event_record("s3:ObjectRemoved:Delete", "b", "x/y"))
        acc = RedisTarget("arn:minio:sqs::2:redis",
                          f"127.0.0.1:{srv.port}", "log",
                          format="access")
        acc.send(event_record("s3:ObjectCreated:Put", "b", "z"))
        deadline = time.monotonic() + 5
        while len(srv.commands) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        cmds = srv.commands
        assert cmds[0][0] == b"HSET" and cmds[0][1] == b"bucketevents" \
            and cmds[0][2] == b"x/y"
        assert json.loads(cmds[0][3])["Records"][0]["eventName"] == \
            "s3:ObjectCreated:Put"
        assert cmds[1][:3] == [b"HDEL", b"bucketevents", b"x/y"]
        assert cmds[2][0] == b"RPUSH" and cmds[2][1] == b"log"
    finally:
        srv.close()


def test_redis_target_error_raises():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def answer():
        conn, _ = srv.accept()
        conn.recv(4096)
        conn.sendall(b"-NOAUTH Authentication required\r\n")
        conn.close()

    threading.Thread(target=answer, daemon=True).start()
    t = RedisTarget("a", f"127.0.0.1:{port}", "k")
    with pytest.raises(OSError, match="NOAUTH"):
        t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    srv.close()


# ---------------------------------------------------------------------------
# MQTT target: real 3.1.1 against an in-process broker
# ---------------------------------------------------------------------------

class FakeMQTT:
    def __init__(self, refuse=False):
        self.refuse = refuse
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.published: list[tuple[str, bytes]] = []
        threading.Thread(target=self._serve, daemon=True).start()

    @staticmethod
    def _read_packet(f):
        h = f.read(1)
        if not h:
            return None, b""
        mult, ln = 1, 0
        while True:
            b = f.read(1)[0]
            ln += (b & 0x7F) * mult
            mult *= 128
            if not b & 0x80:
                break
        return h[0], f.read(ln)

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    ptype, _body = self._read_packet(f)
                    if ptype >> 4 != 1:         # expect CONNECT
                        continue
                    rc = 5 if self.refuse else 0
                    conn.sendall(bytes([0x20, 2, 0, rc]))
                    if self.refuse:
                        continue
                    while True:
                        ptype, body = self._read_packet(f)
                        if ptype is None or ptype >> 4 == 14:  # DISCONNECT
                            break
                        if ptype >> 4 == 3:     # PUBLISH QoS0
                            tl = int.from_bytes(body[:2], "big")
                            topic = body[2:2 + tl].decode()
                            self.published.append((topic, body[2 + tl:]))
                except Exception:
                    pass

    def close(self):
        self.sock.close()


def test_mqtt_target_publish_and_refusal():
    broker = FakeMQTT()
    try:
        t = MQTTTarget("arn:minio:sqs::1:mqtt",
                       f"127.0.0.1:{broker.port}", "minio/events")
        t.send(event_record("s3:ObjectCreated:Put", "b", "mq"))
        deadline = time.monotonic() + 5
        while not broker.published and time.monotonic() < deadline:
            time.sleep(0.01)
        topic, payload = broker.published[0]
        assert topic == "minio/events"
        assert json.loads(payload)["Records"][0]["s3"]["object"]["key"] \
            == "mq"
    finally:
        broker.close()

    refusing = FakeMQTT(refuse=True)
    try:
        t = MQTTTarget("a", f"127.0.0.1:{refusing.port}", "t")
        with pytest.raises(OSError, match="CONNACK"):
            t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    finally:
        refusing.close()


# ---------------------------------------------------------------------------
# Kafka: real produce wire protocol against an in-process fake broker
# ---------------------------------------------------------------------------

class FakeKafkaBroker:
    """Single-node broker speaking the subset the target uses:
    ApiVersions v0, Metadata v1, Produce v2 (MessageSet v1 with CRC
    verification). Stores produced (partition, key, value) tuples."""

    def __init__(self, topic="events", partitions=3,
                 produce_error=0, apiversions=None):
        import struct as st
        self.st = st
        self.topic, self.partitions = topic, partitions
        self.produce_error = produce_error
        self.apiversions = apiversions if apiversions is not None else \
            [(0, 0, 7), (3, 0, 5), (18, 0, 2)]
        self.produced: list[tuple[int, bytes, bytes]] = []
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def close(self):
        self.srv.close()

    # -- wire helpers ------------------------------------------------------

    def _kstr(self, s):
        raw = s.encode()
        return self.st.pack(">h", len(raw)) + raw

    def _read_exact(self, c, n):
        buf = b""
        while len(buf) < n:
            chunk = c.recv(n - len(buf))
            if not chunk:
                raise OSError("closed")
            buf += chunk
        return buf

    def _serve(self):
        while True:
            try:
                c, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._client, args=(c,),
                             daemon=True).start()

    def _client(self, c):
        st = self.st
        try:
            while True:
                (size,) = st.unpack(">i", self._read_exact(c, 4))
                req = self._read_exact(c, size)
                api_key, api_ver, corr = st.unpack(">hhi", req[:8])
                (cid_len,) = st.unpack(">h", req[8:10])
                body = req[10 + max(cid_len, 0):]
                if api_key == 18:                      # ApiVersions
                    resp = st.pack(">h", 0) + st.pack(
                        ">i", len(self.apiversions))
                    for k, lo, hi in self.apiversions:
                        resp += st.pack(">hhh", k, lo, hi)
                elif api_key == 3:                     # Metadata v1
                    resp = st.pack(">i", 1)            # brokers
                    resp += st.pack(">i", 0) + self._kstr("127.0.0.1") \
                        + st.pack(">i", self.port) + st.pack(">h", -1)
                    resp += st.pack(">i", 0)           # controller id
                    resp += st.pack(">i", 1)           # topics
                    resp += st.pack(">h", 0) + self._kstr(self.topic) \
                        + st.pack(">b", 0)
                    resp += st.pack(">i", self.partitions)
                    for pid in range(self.partitions):
                        resp += st.pack(">hii", 0, pid, 0)
                        resp += st.pack(">ii", 1, 0)   # replicas [0]
                        resp += st.pack(">ii", 1, 0)   # isr [0]
                elif api_key == 0:                     # Produce v2
                    resp = self._produce(body)
                else:
                    resp = st.pack(">h", 35)
                payload = st.pack(">i", corr) + resp
                c.sendall(st.pack(">i", len(payload)) + payload)
        except OSError:
            pass
        finally:
            c.close()

    def _produce(self, body):
        import zlib
        st = self.st
        at = 0
        _acks, _timeout = st.unpack(">hi", body[at:at + 6]); at += 6
        (ntopics,) = st.unpack(">i", body[at:at + 4]); at += 4
        assert ntopics == 1
        (tlen,) = st.unpack(">h", body[at:at + 2]); at += 2
        topic = body[at:at + tlen].decode(); at += tlen
        (nparts,) = st.unpack(">i", body[at:at + 4]); at += 4
        assert nparts == 1
        pid, mset_len = st.unpack(">ii", body[at:at + 8]); at += 8
        mset = body[at:at + mset_len]
        # MessageSet v1: offset(8) size(4) crc(4) magic(1) attrs(1)
        # timestamp(8) key value
        _off, _msize = st.unpack(">qi", mset[:12])
        (crc,) = st.unpack(">I", mset[12:16])
        content = mset[16:]
        assert zlib.crc32(content) == crc, "bad message CRC"
        magic, _attrs = st.unpack(">bb", content[:2])
        assert magic == 1
        (klen,) = st.unpack(">i", content[10:14])
        key = content[14:14 + klen]
        vat = 14 + klen
        (vlen,) = st.unpack(">i", content[vat:vat + 4])
        value = content[vat + 4:vat + 4 + vlen]
        if not self.produce_error:
            self.produced.append((pid, key, value))
        resp = st.pack(">i", 1) + self._kstr(topic)
        resp += st.pack(">i", 1)
        resp += st.pack(">ih", pid, self.produce_error)
        resp += st.pack(">qq", len(self.produced) - 1, -1)
        resp += st.pack(">i", 0)                       # throttle
        return resp


def test_kafka_wire_produce_roundtrip():
    broker = FakeKafkaBroker()
    try:
        t = KafkaTarget("arn:minio:sqs::1:kafka",
                        [f"127.0.0.1:{broker.port}"], "events")
        for key in ("kf", "other/key", "third"):
            t.send(event_record("s3:ObjectCreated:Put", "b", key))
        assert len(broker.produced) == 3
        pid, key, value = broker.produced[0]
        assert key == b"kf"
        assert json.loads(value)["Records"][0]["s3"]["object"]["key"] \
            == "kf"
        assert all(0 <= p[0] < 3 for p in broker.produced)
        # sarama-compatible partitioning: abs(int32(fnv1a)) with Go's
        # truncated modulo — deterministic co-partitioning with sarama
        from minio_tpu.features.events import (_fnv1a32,
                                               _sarama_partition)

        def sarama_ref(key, n):
            h = _fnv1a32(key)
            h32 = h - (1 << 32) if h >= (1 << 31) else h
            # Go's % truncates toward zero
            import math
            p = int(math.fmod(h32, n))
            return -p if p < 0 else p

        assert pid == _sarama_partition(b"kf", 3)
        hit_high_bit = False
        for k in (b"kf", b"other/key", b"third", b"\xff\xff", b"",
                  b"a", b"bb", b"ccc"):
            assert _sarama_partition(k, 3) == sarama_ref(k, 3)
            assert 0 <= _sarama_partition(k, 5) < 5
            hit_high_bit |= _fnv1a32(k) >= (1 << 31)
        assert hit_high_bit   # the signed-abs branch was exercised
    finally:
        broker.close()


def test_kafka_wire_error_paths():
    # broker reports a produce error -> OSError -> retry machinery
    failing = FakeKafkaBroker(produce_error=6)   # NOT_LEADER
    try:
        t = KafkaTarget("a", [f"127.0.0.1:{failing.port}"], "events")
        with pytest.raises(OSError, match="produce error 6"):
            t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    finally:
        failing.close()
    # broker too old for Produce v2 -> refused at handshake
    old = FakeKafkaBroker(apiversions=[(0, 0, 1), (3, 0, 5), (18, 0, 2)])
    try:
        t = KafkaTarget("a", [f"127.0.0.1:{old.port}"], "events")
        with pytest.raises(OSError, match="lacks api 0 v2"):
            t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    finally:
        old.close()
    # nothing listening -> no broker reachable
    t = KafkaTarget("a", ["127.0.0.1:1"], "events", timeout=0.5)
    with pytest.raises(OSError, match="no broker reachable"):
        t.send(event_record("s3:ObjectCreated:Put", "b", "k"))


def test_kafka_target_producer_injection():
    sent = []
    t = KafkaTarget("arn:minio:sqs::1:kafka", ["broker:9092"], "events",
                    producer=lambda topic, key, value:
                    sent.append((topic, key, value)))
    t.send(event_record("s3:ObjectCreated:Put", "b", "kf"))
    assert sent[0][0] == "events" and sent[0][1] == b"kf"
    assert json.loads(sent[0][2])["Records"][0]["s3"]["object"]["key"] \
        == "kf"


# ---------------------------------------------------------------------------
# replication durability across restart
# ---------------------------------------------------------------------------

def test_replication_survives_restart(tmp_path):
    """Replication queued while the destination is down must be
    re-driven by a NEW pool over the same queue dir after 'restart'
    (VERDICT r2 weak #6)."""
    from minio_tpu.features.replication import (ReplicationPool,
                                                ReplicationTarget)
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    from tests.test_features import REPL_XML, _mk_sets
    from minio_tpu.s3.handlers import S3ApiHandlers

    creds = Credentials("replsrckey1", "replsrcsecret1")
    src = _mk_sets(tmp_path / "src")
    dst = _mk_sets(tmp_path / "dst")
    qdir = str(tmp_path / "replq")
    try:
        src.make_bucket("srcb")
        dst.make_bucket("dstb")
        api = S3ApiHandlers(src, creds=creds)
        api.bucket_meta.update("srcb", replication_xml=REPL_XML)
        src.put_object("srcb", "obj1", b"durable repl")

        # pool 1: destination server NOT running -> replication fails,
        # task stays persisted
        pool1 = ReplicationPool(src, api.bucket_meta, queue_dir=qdir,
                                redrive_interval=3600)
        pool1.register_target(ReplicationTarget(
            arn="arn:minio:replication::dst:target",
            host="127.0.0.1", port=1, bucket="dstb",
            access_key=creds.access_key, secret_key=creds.secret_key))
        pool1.on_put("srcb", "obj1")
        pool1.drain()
        assert pool1.replicated == 0 and pool1.failed >= 1
        assert len(pool1.store.keys()) == 1
        pool1.close()                      # "process dies"

        # pool 2 over the same dir, destination now up
        dst_srv = S3Server(dst, creds=creds).start()
        try:
            pool2 = ReplicationPool(src, api.bucket_meta,
                                    queue_dir=qdir,
                                    redrive_interval=3600)
            pool2.register_target(ReplicationTarget(
                arn="arn:minio:replication::dst:target",
                host="127.0.0.1", port=dst_srv.port, bucket="dstb",
                access_key=creds.access_key,
                secret_key=creds.secret_key))
            deadline = time.monotonic() + 10
            while pool2.replicated < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            _, stream = dst.get_object("dstb", "obj1")
            assert b"".join(stream) == b"durable repl"
            assert pool2.store.keys() == []      # acked -> store empty
            pool2.close()
        finally:
            dst_srv.stop()
    finally:
        src.close()
        dst.close()


# ---------------------------------------------------------------------------
# NATS target: real text protocol against an in-process server
# ---------------------------------------------------------------------------

class FakeNATS:
    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.published: list[tuple[str, bytes]] = []
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    conn.sendall(b'INFO {"server_id":"fake"}\r\n')
                    f = conn.makefile("rb")
                    line = f.readline()          # CONNECT {...}
                    assert line.startswith(b"CONNECT")
                    conn.sendall(b"+OK\r\n")
                    line = f.readline()          # PUB subj n
                    parts = line.split()
                    if parts and parts[0] == b"PUB":
                        n = int(parts[2])
                        payload = f.read(n + 2)[:-2]
                        self.published.append(
                            (parts[1].decode(), payload))
                        conn.sendall(b"+OK\r\n")
                except Exception:
                    pass

    def close(self):
        self.sock.close()


def test_nats_target_publish():
    from minio_tpu.features.events import NATSTarget
    srv = FakeNATS()
    try:
        t = NATSTarget("arn:minio:sqs::1:nats",
                       f"127.0.0.1:{srv.port}", "minio.events")
        t.send(event_record("s3:ObjectCreated:Put", "b", "nt"))
        deadline = time.monotonic() + 5
        while not srv.published and time.monotonic() < deadline:
            time.sleep(0.01)
        subj, payload = srv.published[0]
        assert subj == "minio.events"
        assert json.loads(payload)["Records"][0]["s3"]["object"]["key"] \
            == "nt"
    finally:
        srv.close()

    # a non-NATS endpoint is rejected cleanly
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead.listen(1)
    port = dead.getsockname()[1]

    def junk():
        conn, _ = dead.accept()
        conn.sendall(b"HTTP/1.1 400 nope\r\n\r\n")
        conn.close()

    threading.Thread(target=junk, daemon=True).start()
    from minio_tpu.features.events import NATSTarget as NT
    with pytest.raises(OSError, match="not a NATS server"):
        NT("a", f"127.0.0.1:{port}", "s").send(
            event_record("s3:ObjectCreated:Put", "b", "k"))
    dead.close()


# ---------------------------------------------------------------------------
# Elasticsearch target: document API against an in-process HTTP server
# ---------------------------------------------------------------------------

def test_elasticsearch_target_namespace_and_access():
    import http.server
    from minio_tpu.features.events import ElasticsearchTarget

    calls: list[tuple[str, str, bytes]] = []

    class ES(http.server.BaseHTTPRequestHandler):
        def _h(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            body = self.rfile.read(n) if n else b""
            calls.append((self.command, self.path, body))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")
        do_PUT = do_POST = do_DELETE = _h

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), ES)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        t = ElasticsearchTarget("arn:minio:sqs::1:elasticsearch", url,
                                "events")
        t.send(event_record("s3:ObjectCreated:Put", "b", "x/y"))
        t.send(event_record("s3:ObjectRemoved:Delete", "b", "x/y"))
        acc = ElasticsearchTarget("a2", url, "log", format="access")
        acc.send(event_record("s3:ObjectCreated:Put", "b", "z"))
        assert calls[0][0] == "PUT" and \
            calls[0][1] == "/events/_doc/b%2Fx%2Fy"
        assert json.loads(calls[0][2])["Records"][0]["eventName"] == \
            "s3:ObjectCreated:Put"
        assert calls[1][0] == "DELETE" and \
            calls[1][1] == "/events/_doc/b%2Fx%2Fy"
        assert calls[2][0] == "POST" and calls[2][1] == "/log/_doc"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# NSQ target: real TCP protocol against an in-process nsqd
# ---------------------------------------------------------------------------

def test_nsq_target_publish():
    from minio_tpu.features.events import NSQTarget

    published = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    assert f.read(4) == b"  V2"
                    line = f.readline()           # PUB <topic>\n
                    assert line.startswith(b"PUB ")
                    topic = line.split()[1].decode()
                    n = int.from_bytes(f.read(4), "big")
                    body = f.read(n)
                    published.append((topic, body))
                    data = b"OK"
                    conn.sendall(
                        (len(data) + 4).to_bytes(4, "big")
                        + (0).to_bytes(4, "big") + data)
                except Exception:
                    pass

    threading.Thread(target=serve, daemon=True).start()
    try:
        t = NSQTarget("arn:minio:sqs::1:nsq", f"127.0.0.1:{port}",
                      "minio-events")
        t.send(event_record("s3:ObjectCreated:Put", "b", "nq"))
        deadline = time.monotonic() + 5
        while not published and time.monotonic() < deadline:
            time.sleep(0.01)
        topic, payload = published[0]
        assert topic == "minio-events"
        assert json.loads(payload)["Records"][0]["s3"]["object"]["key"] \
            == "nq"
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# AMQP target: real 0-9-1 handshake + publish against a fake broker
# ---------------------------------------------------------------------------

class FakeAMQP:
    """Speaks enough broker-side AMQP 0-9-1 to accept one publish."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.published: list[tuple[str, bytes]] = []
        self.auth: list[bytes] = []
        threading.Thread(target=self._serve, daemon=True).start()

    @staticmethod
    def _frame(ftype, channel, payload):
        return (bytes([ftype]) + channel.to_bytes(2, "big")
                + len(payload).to_bytes(4, "big") + payload + b"\xce")

    @classmethod
    def _method(cls, channel, c, m, args=b""):
        return cls._frame(1, channel, c.to_bytes(2, "big")
                          + m.to_bytes(2, "big") + args)

    @staticmethod
    def _read_frame(f):
        head = f.read(7)
        if len(head) < 7:
            return None, None, None
        size = int.from_bytes(head[3:7], "big")
        payload = f.read(size)
        assert f.read(1) == b"\xce"
        return head[0], int.from_bytes(head[1:3], "big"), payload

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    assert f.read(8) == b"AMQP\x00\x00\x09\x01"
                    conn.sendall(self._method(
                        0, 10, 10,
                        b"\x00\x09" + (0).to_bytes(4, "big")
                        + (5).to_bytes(4, "big") + b"PLAIN"
                        + (5).to_bytes(4, "big") + b"en_US"))
                    _t, _c, p = self._read_frame(f)   # Start-Ok
                    self.auth.append(p)
                    conn.sendall(self._method(
                        0, 10, 30, (0).to_bytes(2, "big")
                        + (131072).to_bytes(4, "big")
                        + (0).to_bytes(2, "big")))
                    self._read_frame(f)               # Tune-Ok
                    self._read_frame(f)               # Open
                    conn.sendall(self._method(0, 10, 41, b"\x00"))
                    self._read_frame(f)               # Channel.Open
                    conn.sendall(self._method(
                        1, 20, 11, (0).to_bytes(4, "big")))
                    _t, _c, pub = self._read_frame(f)  # Basic.Publish
                    at = 6                     # cls+meth+reserved
                    elen = pub[at]
                    at += 1 + elen
                    rlen = pub[at]
                    rkey = pub[at + 1:at + 1 + rlen].decode()
                    _t, _c, hdr = self._read_frame(f)  # content header
                    body_size = int.from_bytes(hdr[4:12], "big")
                    body = b""
                    while len(body) < body_size:       # chunked frames
                        _t, _c, piece = self._read_frame(f)
                        body += piece
                    self.published.append((rkey, body))
                    self._read_frame(f)                # Connection.Close
                    conn.sendall(self._method(0, 10, 51))  # Close-Ok
                except Exception:
                    pass

    def close(self):
        self.sock.close()


def test_amqp_target_publish():
    from minio_tpu.features.events import AMQPTarget
    broker = FakeAMQP()
    try:
        t = AMQPTarget("arn:minio:sqs::1:amqp",
                       f"127.0.0.1:{broker.port}",
                       routing_key="minio.amqp", user="u1",
                       password="p1")
        t.send(event_record("s3:ObjectCreated:Put", "b", "aq"))
        deadline = time.monotonic() + 5
        while not broker.published and time.monotonic() < deadline:
            time.sleep(0.01)
        rkey, body = broker.published[0]
        assert rkey == "minio.amqp"
        assert json.loads(body)["Records"][0]["s3"]["object"]["key"] \
            == "aq"
        # PLAIN credentials travelled in Start-Ok
        assert b"\x00u1\x00p1" in broker.auth[0]
    finally:
        broker.close()


def test_amqp_publish_refused_surfaces_error():
    """A broker that answers with Channel.Close (unroutable exchange)
    must make send() raise — fire-and-forget would delete the event
    from the durable queue despite the loss (review r3)."""
    from minio_tpu.features.events import AMQPTarget
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        conn, _ = srv.accept()
        with conn:
            f = conn.makefile("rb")
            f.read(8)
            conn.sendall(FakeAMQP._method(
                0, 10, 10, b"\x00\x09" + (0).to_bytes(4, "big")
                + (5).to_bytes(4, "big") + b"PLAIN"
                + (5).to_bytes(4, "big") + b"en_US"))
            FakeAMQP._read_frame(f)               # Start-Ok
            conn.sendall(FakeAMQP._method(
                0, 10, 30, (0).to_bytes(2, "big")
                + (4096).to_bytes(4, "big") + (0).to_bytes(2, "big")))
            FakeAMQP._read_frame(f)               # Tune-Ok
            FakeAMQP._read_frame(f)               # Open
            conn.sendall(FakeAMQP._method(0, 10, 41, b"\x00"))
            FakeAMQP._read_frame(f)               # Channel.Open
            conn.sendall(FakeAMQP._method(
                1, 20, 11, (0).to_bytes(4, "big")))
            # drain publish + header + body frames, then refuse
            while True:
                t, _c, p = FakeAMQP._read_frame(f)
                if t == 1 and p[:4] == (10).to_bytes(2, "big") \
                        + (50).to_bytes(2, "big"):
                    break
            conn.sendall(FakeAMQP._method(
                1, 20, 40, (404).to_bytes(2, "big")
                + bytes([9]) + b"NOT_FOUND"
                + (60).to_bytes(2, "big") + (40).to_bytes(2, "big")))

    threading.Thread(target=serve, daemon=True).start()
    t = AMQPTarget("a", f"127.0.0.1:{port}")
    with pytest.raises(OSError, match="refused"):
        t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    srv.close()


def test_amqp_nsq_config_validation():
    from minio_tpu.features.events import AMQPTarget, NSQTarget
    with pytest.raises(ValueError):
        NSQTarget("a", "h:4150", "bad topic")
    with pytest.raises(ValueError):
        NSQTarget("a", "h:4150", "")
    with pytest.raises(ValueError):
        AMQPTarget("a", "h:5672", routing_key="x" * 300)
    with pytest.raises(ValueError):
        AMQPTarget("a", "h:5672", exchange="e\nvil")


# ---------------------------------------------------------------------------
# Postgres target: real v3 wire protocol against a fake server
# ---------------------------------------------------------------------------

class FakePostgres:
    """Speaks enough server-side pg v3: startup, md5 or SCRAM-SHA-256
    auth challenge, simple-query with OK/error replies."""

    def __init__(self, password: str = "", auth: str = "md5"):
        self.password = password
        self.auth = auth
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.queries: list[str] = []
        self.fail_next: bool = False
        threading.Thread(target=self._serve, daemon=True).start()

    @staticmethod
    def _msg(tag: bytes, payload: bytes = b"") -> bytes:
        return tag + (len(payload) + 4).to_bytes(4, "big") + payload

    def _serve(self):
        import hashlib as hl
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    size = int.from_bytes(f.read(4), "big")
                    startup = f.read(size - 4)
                    params = startup[4:].split(b"\x00")
                    user = params[params.index(b"user") + 1].decode()
                    if self.password and self.auth == "md5":
                        salt = b"SALT"
                        conn.sendall(self._msg(
                            b"R", (5).to_bytes(4, "big") + salt))
                        tag = f.read(1)
                        n = int.from_bytes(f.read(4), "big")
                        pw = f.read(n - 4).rstrip(b"\x00")
                        inner = hl.md5(self.password.encode()
                                       + user.encode()).hexdigest()
                        want = b"md5" + hl.md5(
                            inner.encode() + salt).hexdigest().encode()
                        if tag != b"p" or pw != want:
                            conn.sendall(self._msg(
                                b"E", b"SFATAL\x00Mbad password\x00\x00"))
                            continue
                    elif self.password and self.auth == "scram":
                        if not self._scram(conn, f):
                            continue
                    conn.sendall(self._msg(b"R", (0).to_bytes(4, "big")))
                    conn.sendall(self._msg(b"Z", b"I"))
                    while True:
                        tag = f.read(1)
                        if not tag or tag == b"X":
                            break
                        n = int.from_bytes(f.read(4), "big")
                        payload = f.read(n - 4)
                        if tag != b"Q":
                            continue
                        sql = payload.rstrip(b"\x00").decode()
                        self.queries.append(sql)
                        if self.fail_next:
                            self.fail_next = False
                            conn.sendall(self._msg(
                                b"E", b"SERROR\x00Mno such table\x00\x00"))
                        else:
                            conn.sendall(self._msg(b"C", b"INSERT 0 1\x00"))
                        conn.sendall(self._msg(b"Z", b"I"))
                except Exception:
                    pass

    def _scram(self, conn, f) -> bool:
        """Server-side SCRAM-SHA-256 (RFC 7677) with real proof
        verification — a client that fakes any step fails here."""
        import base64 as b64
        import hashlib as hl
        import hmac as hm
        import os as _os
        conn.sendall(self._msg(
            b"R", (10).to_bytes(4, "big") + b"SCRAM-SHA-256\x00\x00"))
        tag = f.read(1)
        n = int.from_bytes(f.read(4), "big")
        body = f.read(n - 4)
        mech_end = body.index(b"\x00")
        assert body[:mech_end] == b"SCRAM-SHA-256"
        ilen = int.from_bytes(body[mech_end + 1:mech_end + 5], "big")
        client_first = body[mech_end + 5:mech_end + 5 + ilen].decode()
        assert tag == b"p" and client_first.startswith("n,,")
        first_bare = client_first[3:]
        cnonce = dict(kv.split("=", 1)
                      for kv in first_bare.split(","))["r"]
        salt = b"scram-salt-16byte"
        iters = 4096
        srv_nonce = cnonce + b64.b64encode(_os.urandom(9)).decode()
        server_first = (f"r={srv_nonce},"
                        f"s={b64.b64encode(salt).decode()},i={iters}")
        conn.sendall(self._msg(
            b"R", (11).to_bytes(4, "big") + server_first.encode()))
        tag = f.read(1)
        n = int.from_bytes(f.read(4), "big")
        client_final = f.read(n - 4).decode()
        assert tag == b"p"
        final_bare, _, proof_b64 = client_final.rpartition(",p=")
        salted = hl.pbkdf2_hmac("sha256", self.password.encode(),
                                salt, iters)
        ckey = hm.new(salted, b"Client Key", hl.sha256).digest()
        stored = hl.sha256(ckey).digest()
        auth_msg = ",".join((first_bare, server_first,
                             final_bare)).encode()
        csig = hm.new(stored, auth_msg, hl.sha256).digest()
        want = bytes(a ^ b for a, b in zip(ckey, csig))
        if b64.b64decode(proof_b64) != want:
            conn.sendall(self._msg(
                b"E", b"SFATAL\x00Mbad scram proof\x00\x00"))
            return False
        skey = hm.new(salted, b"Server Key", hl.sha256).digest()
        ssig = hm.new(skey, auth_msg, hl.sha256).digest()
        conn.sendall(self._msg(
            b"R", (12).to_bytes(4, "big") + b"v="
            + b64.b64encode(ssig)))
        return True

    def close(self):
        self.sock.close()


def test_postgres_scram_sha256_auth():
    """Modern server default (VERDICT r3 weak #8): full SCRAM-SHA-256
    exchange with mutual proof verification."""
    from minio_tpu.features.events import PostgresTarget
    srv = FakePostgres(password="pgpass", auth="scram")
    try:
        t = PostgresTarget("arn:minio:sqs::1:postgresql",
                           f"127.0.0.1:{srv.port}", "minio", "events",
                           user="minio", password="pgpass")
        t.send(event_record("s3:ObjectCreated:Put", "b", "scrammed"))
        assert srv.queries and "scrammed" in srv.queries[0]
        bad = PostgresTarget("a2", f"127.0.0.1:{srv.port}", "minio",
                             "events", user="minio", password="wrong")
        with pytest.raises(OSError, match="postgres error"):
            bad.send(event_record("s3:ObjectCreated:Put", "b", "k"))
    finally:
        srv.close()


def test_postgres_target_md5_auth_and_formats():
    from minio_tpu.features.events import PostgresTarget
    srv = FakePostgres(password="pgpass")
    try:
        t = PostgresTarget("arn:minio:sqs::1:postgresql",
                           f"127.0.0.1:{srv.port}", "minio", "events",
                           user="minio", password="pgpass")
        t.send(event_record("s3:ObjectCreated:Put", "b", "x'y"))
        t.send(event_record("s3:ObjectRemoved:Delete", "b", "x'y"))
        acc = PostgresTarget("a2", f"127.0.0.1:{srv.port}", "minio",
                             "log", user="minio", password="pgpass",
                             format="access")
        acc.send(event_record("s3:ObjectCreated:Put", "b", "z"))
        stmts = srv.queries
        assert stmts[0].startswith(
            "INSERT INTO events (key, value) VALUES ('b/x''y'")
        assert "ON CONFLICT" in stmts[0]
        assert stmts[1] == "DELETE FROM events WHERE key = 'b/x''y'"
        assert stmts[2].startswith(
            "INSERT INTO log (event_time, event_data) VALUES (now()")

        # SQL errors surface (durable queue must retry, not ack)
        srv.fail_next = True
        with pytest.raises(OSError, match="query failed"):
            t.send(event_record("s3:ObjectCreated:Put", "b", "k"))
        # wrong password -> auth error
        bad = PostgresTarget("a3", f"127.0.0.1:{srv.port}", "minio",
                             "events", user="minio", password="wrong")
        with pytest.raises(OSError, match="postgres error"):
            bad.send(event_record("s3:ObjectCreated:Put", "b", "k"))
        # injection-shaped table names rejected at config time
        with pytest.raises(ValueError):
            PostgresTarget("a4", "h:5432", "db", "evil; DROP TABLE x")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# MySQL target: handshake v10 + native-password auth + COM_QUERY
# ---------------------------------------------------------------------------

class FakeMySQL:
    def __init__(self, password: str = "", auth: str = "native"):
        # auth: native | sha2_fast | sha2_full | switch_native
        self.password = password
        self.auth = auth
        self.salt = b"abcdefgh" + b"ijklmnopqrst"   # 8 + 12 bytes
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.queries: list[str] = []
        threading.Thread(target=self._serve, daemon=True).start()

    @staticmethod
    def _packet(seq, payload):
        return (len(payload).to_bytes(3, "little") + bytes([seq])
                + payload)

    @staticmethod
    def _read(f):
        head = f.read(4)
        if len(head) < 4:
            return None
        return f.read(int.from_bytes(head[:3], "little"))

    def _expected_token(self, user, salt=None):
        import hashlib as hl
        if not self.password:
            return b""
        salt = salt if salt is not None else self.salt
        h1 = hl.sha1(self.password.encode()).digest()
        h2 = hl.sha1(h1).digest()
        h3 = hl.sha1(salt + h2).digest()
        return bytes(a ^ b for a, b in zip(h1, h3))

    def _expected_sha2(self, salt=None):
        import hashlib as hl
        if not self.password:
            return b""
        salt = salt if salt is not None else self.salt
        h1 = hl.sha256(self.password.encode()).digest()
        h2 = hl.sha256(hl.sha256(h1).digest() + salt).digest()
        return bytes(a ^ b for a, b in zip(h1, h2))

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    f = conn.makefile("rb")
                    plugin = b"mysql_native_password" \
                        if self.auth == "native" \
                        else b"caching_sha2_password"
                    greet = (b"\x0a" + b"8.0.0-fake\x00"
                             + (7).to_bytes(4, "little")
                             + self.salt[:8] + b"\x00"
                             + (0xffff).to_bytes(2, "little")
                             + bytes([33])
                             + (2).to_bytes(2, "little")
                             + (0x8000 >> 16).to_bytes(2, "little")
                             + bytes([21]) + bytes(10)
                             + self.salt[8:] + b"\x00"
                             + plugin + b"\x00")
                    conn.sendall(self._packet(0, greet))
                    resp = self._read(f)
                    user_end = resp.index(b"\x00", 32)
                    user = resp[32:user_end].decode()
                    tlen = resp[user_end + 1]
                    token = resp[user_end + 2:user_end + 2 + tlen]
                    if self.auth == "switch_native":
                        # ask the client to fall back to native with a
                        # fresh nonce (AuthSwitchRequest)
                        new_salt = b"ZYXWVUTSRQPONMLKJIHG"
                        conn.sendall(self._packet(
                            2, b"\xfe" + b"mysql_native_password\x00"
                            + new_salt + b"\x00"))
                        token = self._read(f)
                        if token != self._expected_token(user,
                                                         new_salt):
                            conn.sendall(self._packet(
                                4, b"\xff"
                                + (1045).to_bytes(2, "little")
                                + b"#28000" + b"Access denied"))
                            continue
                        conn.sendall(self._packet(
                            4, b"\x00\x00\x00\x02\x00\x00\x00"))
                    elif self.auth in ("sha2_fast", "sha2_full"):
                        if token != self._expected_sha2():
                            conn.sendall(self._packet(
                                2, b"\xff"
                                + (1045).to_bytes(2, "little")
                                + b"#28000" + b"Access denied"))
                            continue
                        if self.auth == "sha2_full":
                            conn.sendall(self._packet(2, b"\x01\x04"))
                            continue
                        conn.sendall(self._packet(2, b"\x01\x03"))
                        conn.sendall(self._packet(
                            3, b"\x00\x00\x00\x02\x00\x00\x00"))
                    else:
                        if token != self._expected_token(user):
                            conn.sendall(self._packet(
                                2, b"\xff"
                                + (1045).to_bytes(2, "little")
                                + b"#28000" + b"Access denied"))
                            continue
                        conn.sendall(self._packet(
                            2, b"\x00\x00\x00\x02\x00\x00\x00"))
                    while True:
                        cmd = self._read(f)
                        if cmd is None or cmd[:1] == b"\x01":
                            break
                        if cmd[:1] == b"\x03":
                            self.queries.append(cmd[1:].decode())
                            conn.sendall(self._packet(
                                1, b"\x00\x01\x00\x02\x00\x00\x00"))
                except Exception:
                    pass

    def close(self):
        self.sock.close()


def test_mysql_target_auth_and_formats():
    from minio_tpu.features.events import MySQLTarget
    srv = FakeMySQL(password="mypass")
    try:
        t = MySQLTarget("arn:minio:sqs::1:mysql",
                        f"127.0.0.1:{srv.port}", "minio", "events",
                        user="minio", password="mypass")
        t.send(event_record("s3:ObjectCreated:Put", "b", "m'y\\k"))
        t.send(event_record("s3:ObjectRemoved:Delete", "b", "m'y\\k"))
        sets = [q for q in srv.queries if q.startswith("SET SESSION")]
        stmts = [q for q in srv.queries if not q.startswith("SET ")]
        assert len(sets) == 2      # sql_mode pinned per connection
        assert not any(q.startswith("USE ") for q in srv.queries)
        # NO_BACKSLASH_ESCAPES pinned => quote doubling only, the
        # backslash in the key stays single
        assert stmts[0].startswith(
            "REPLACE INTO events (`key`, value) VALUES "
            "('b/m''y\\k'")
        assert stmts[1] == "DELETE FROM events WHERE `key` = " \
            "'b/m''y\\k'"

        bad = MySQLTarget("a2", f"127.0.0.1:{srv.port}", "minio",
                          "events", user="minio", password="wrong")
        with pytest.raises(OSError, match="auth failed"):
            bad.send(event_record("s3:ObjectCreated:Put", "b", "k"))
        with pytest.raises(ValueError):
            MySQLTarget("a3", "h:3306", "db", "bad table")
    finally:
        srv.close()


def test_mysql_caching_sha2_password():
    """MySQL 8.0 default auth (VERDICT r3 weak #8): sha2 scramble with
    fast-auth success, the full-auth path failing with a clear action,
    and the server-initiated switch back to native."""
    from minio_tpu.features.events import MySQLTarget
    rec = event_record("s3:ObjectCreated:Put", "b", "sha2key")

    fast = FakeMySQL(password="mypass", auth="sha2_fast")
    try:
        t = MySQLTarget("a", f"127.0.0.1:{fast.port}", "minio",
                        "events", user="minio", password="mypass")
        t.send(rec)
        assert any("sha2key" in q for q in fast.queries)
        bad = MySQLTarget("a2", f"127.0.0.1:{fast.port}", "minio",
                          "events", user="minio", password="wrong")
        with pytest.raises(OSError, match="auth failed"):
            bad.send(rec)
    finally:
        fast.close()

    full = FakeMySQL(password="mypass", auth="sha2_full")
    try:
        t = MySQLTarget("a", f"127.0.0.1:{full.port}", "minio",
                        "events", user="minio", password="mypass")
        with pytest.raises(OSError, match="requires TLS"):
            t.send(rec)
    finally:
        full.close()

    switch = FakeMySQL(password="mypass", auth="switch_native")
    try:
        t = MySQLTarget("a", f"127.0.0.1:{switch.port}", "minio",
                        "events", user="minio", password="mypass")
        t.send(rec)
        assert any("sha2key" in q for q in switch.queries)
    finally:
        switch.close()
