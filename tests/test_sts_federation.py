"""STS federation: OpenID (AssumeRoleWithWebIdentity / ClientGrants)
and LDAP (AssumeRoleWithLDAPIdentity) — VERDICT r2 item 2, reference
cmd/sts-handlers.go:43-86 + cmd/config/identity/{openid,ldap}.

Covers token-validation failure modes, policy-claim mapping, the LDAP
BER simple-bind against an in-process LDAPv3 server, and the full HTTP
flow: federated mint -> minted creds exercise their mapped policies.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import socket
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.iam import IAMSys
from minio_tpu.iam.providers import (LDAPProvider, OpenIDProvider,
                                     STSValidationError, _parse_tlv)
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

from tests.test_iam import CREDS, REGION, Client, object_layer  # noqa: F401

HS_SECRET = b"sts-test-secret-0123456789abcdef"


def b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def make_jwt(claims: dict, *, alg: str = "HS256", kid: str = "k1",
             secret: bytes = HS_SECRET, rsa_key=None,
             tamper: bool = False) -> str:
    header = {"alg": alg, "typ": "JWT"}
    if kid:
        header["kid"] = kid
    h = b64url(json.dumps(header).encode())
    p = b64url(json.dumps(claims).encode())
    signing = f"{h}.{p}".encode()
    if alg.startswith("HS"):
        digest = {"HS256": "sha256", "HS384": "sha384",
                  "HS512": "sha512"}[alg]
        sig = hmac.new(secret, signing, getattr(hashlib, digest)).digest()
    else:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        sig = rsa_key.sign(signing, padding.PKCS1v15(), hashes.SHA256())
    if tamper:
        sig = bytes([sig[0] ^ 1]) + sig[1:]
    return f"{h}.{p}.{b64url(sig)}"


def hs_jwks() -> str:
    return json.dumps({"keys": [{
        "kty": "oct", "kid": "k1", "k": b64url(HS_SECRET)}]})


@pytest.fixture()
def provider():
    return OpenIDProvider({"jwks": hs_jwks(), "client_id": "minio-app"})


def claims(**over):
    c = {"sub": "alice@example.org", "aud": "minio-app",
         "exp": time.time() + 600, "policy": "readwrite"}
    c.update(over)
    return c


# ---------------------------------------------------------------------------
# OpenID token validation
# ---------------------------------------------------------------------------

def test_openid_happy_path(provider):
    got = provider.validate(make_jwt(claims()))
    assert got["sub"] == "alice@example.org"
    assert provider.policy_names(got) == ["readwrite"]


def test_openid_failure_modes(provider):
    with pytest.raises(STSValidationError, match="malformed"):
        provider.validate("not-a-jwt")
    with pytest.raises(STSValidationError, match="expired"):
        provider.validate(make_jwt(claims(exp=time.time() - 5)))
    with pytest.raises(STSValidationError, match="missing exp"):
        c = claims()
        del c["exp"]
        provider.validate(make_jwt(c))
    with pytest.raises(STSValidationError, match="not yet valid"):
        provider.validate(make_jwt(claims(nbf=time.time() + 500)))
    with pytest.raises(STSValidationError, match="audience"):
        provider.validate(make_jwt(claims(aud="other-app")))
    with pytest.raises(STSValidationError, match="signature"):
        provider.validate(make_jwt(claims(), tamper=True))
    with pytest.raises(STSValidationError, match="signature"):
        provider.validate(make_jwt(claims(), secret=b"wrong-secret"))
    with pytest.raises(STSValidationError, match="unknown kid"):
        provider.validate(make_jwt(claims(), kid="nope"))
    with pytest.raises(STSValidationError, match="unsupported alg"):
        t = make_jwt(claims())
        h = b64url(json.dumps({"alg": "none"}).encode())
        provider.validate(h + t[t.index("."):])


def test_openid_policy_claim_shapes():
    p = OpenIDProvider({"jwks": hs_jwks()})
    assert p.policy_names({"policy": "a, b ,c"}) == ["a", "b", "c"]
    assert p.policy_names({"policy": ["x", "y"]}) == ["x", "y"]
    assert p.policy_names({}) == []
    pfx = OpenIDProvider({"jwks": hs_jwks(),
                          "claim_prefix": "https://minio/"})
    assert pfx.policy_names({"https://minio/policy": "p1"}) == ["p1"]


def test_openid_rs256_roundtrip():
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()

    def uint_b64(v: int) -> str:
        return b64url(v.to_bytes((v.bit_length() + 7) // 8, "big"))

    jwks = json.dumps({"keys": [{"kty": "RSA", "kid": "r1",
                                 "n": uint_b64(pub.n),
                                 "e": uint_b64(pub.e)}]})
    p = OpenIDProvider({"jwks": jwks})
    tok = make_jwt(claims(), alg="RS256", kid="r1", rsa_key=key)
    assert p.validate(tok)["policy"] == "readwrite"
    with pytest.raises(STSValidationError, match="signature"):
        p.validate(make_jwt(claims(), alg="RS256", kid="r1",
                            rsa_key=key, tamper=True))


# ---------------------------------------------------------------------------
# LDAP: BER simple bind against an in-process LDAPv3 server
# ---------------------------------------------------------------------------

class FakeLDAPServer:
    """Loopback LDAPv3 subset: parses a real BER BindRequest, answers
    success (resultCode 0) or invalidCredentials (49)."""

    def __init__(self, accounts: dict[str, str], fragment: bool = False):
        self.accounts = accounts
        self.fragment = fragment       # drip the response byte-by-byte
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.binds: list[str] = []
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    req = conn.recv(4096)
                    _t, env, _ = _parse_tlv(req, 0)
                    at = 0
                    _t, msgid, at = _parse_tlv(env, at)
                    tag, bind, _ = _parse_tlv(env, at)
                    assert tag == 0x60
                    at2 = 0
                    _t, _ver, at2 = _parse_tlv(bind, at2)
                    _t, dn, at2 = _parse_tlv(bind, at2)
                    _t, pw, _ = _parse_tlv(bind, at2)
                    dn_s, pw_s = dn.decode(), pw.decode()
                    self.binds.append(dn_s)
                    code = 0 if self.accounts.get(dn_s) == pw_s else 49
                    # BindResponse: resultCode ENUM, matchedDN, diag
                    body = (bytes([0x0A, 1, code])
                            + bytes([0x04, 0]) + bytes([0x04, 0]))
                    payload = (b"\x02" + bytes([len(msgid)]) + msgid
                               + bytes([0x61, len(body)]) + body)
                    out = bytes([0x30, len(payload)]) + payload
                    if self.fragment:
                        for i in range(len(out)):
                            conn.sendall(out[i:i + 1])
                            time.sleep(0.002)
                    else:
                        conn.sendall(out)
                except Exception:
                    pass

    def close(self):
        self.sock.close()


@pytest.fixture()
def ldap_server():
    s = FakeLDAPServer(
        {"uid=bob,ou=people,dc=example,dc=org": "bobsecret"})
    yield s
    s.close()


def test_ldap_bind_success_and_failures(ldap_server):
    p = LDAPProvider({
        "server_addr": f"127.0.0.1:{ldap_server.port}",
        "user_dn_format": "uid=%s,ou=people,dc=example,dc=org"})
    dn = p.bind("bob", "bobsecret")
    assert dn == "uid=bob,ou=people,dc=example,dc=org"
    with pytest.raises(STSValidationError, match="resultCode 49"):
        p.bind("bob", "wrong")
    with pytest.raises(STSValidationError, match="resultCode 49"):
        p.bind("mallory", "bobsecret")
    with pytest.raises(STSValidationError, match="empty"):
        p.bind("bob", "")
    dead = LDAPProvider({"server_addr": "127.0.0.1:1",
                         "user_dn_format": "uid=%s"})
    with pytest.raises(STSValidationError, match="unreachable"):
        dead.bind("bob", "pw")


# ---------------------------------------------------------------------------
# end-to-end over HTTP: federated mint -> mapped policy enforcement
# ---------------------------------------------------------------------------

def sts_post(port, form: dict) -> tuple[int, bytes]:
    import http.client
    body = urllib.parse.urlencode(form).encode()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/", body=body, headers={
        "Host": f"127.0.0.1:{port}",
        "Content-Type": "application/x-www-form-urlencoded"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def parse_sts_creds(body: bytes) -> Credentials:
    ns = {"sts": "https://sts.amazonaws.com/doc/2011-06-15/"}
    root = ET.fromstring(body)
    c = root.find(".//sts:Credentials", ns)
    return Credentials(
        access_key=c.find("sts:AccessKeyId", ns).text,
        secret_key=c.find("sts:SecretAccessKey", ns).text,
        session_token=c.find("sts:SessionToken", ns).text)


@pytest.fixture()
def fed_server(object_layer):  # noqa: F811
    iam = IAMSys(object_layer, root_cred=CREDS)
    srv = S3Server(object_layer, creds=CREDS, region=REGION,
                   iam=iam).start()
    srv.api.openid_provider = OpenIDProvider(
        {"jwks": hs_jwks(), "client_id": "minio-app"})
    yield srv, iam
    srv.stop()


def test_e2e_web_identity(fed_server):
    srv, iam = fed_server
    root = Client(srv.port, CREDS)
    assert root.request("PUT", "/fedbucket")[0] == 200
    assert root.request("PUT", "/fedbucket/o", body=b"fed")[0] == 200

    # unsigned POST with a valid token carrying policy=readonly
    tok = make_jwt(claims(policy="readonly"))
    st, body = sts_post(srv.port, {
        "Action": "AssumeRoleWithWebIdentity", "Version": "2011-06-15",
        "WebIdentityToken": tok, "DurationSeconds": "900"})
    assert st == 200, body
    assert b"SubjectFromWebIdentityToken" in body
    temp = Client(srv.port, parse_sts_creds(body))

    st, got = temp.request("GET", "/fedbucket/o")
    assert st == 200 and got == b"fed"
    # readonly: writes denied
    assert temp.request("PUT", "/fedbucket/w", body=b"x")[0] == 403

    # expired/tampered/no-policy tokens are rejected
    for bad in (make_jwt(claims(exp=time.time() - 5)),
                make_jwt(claims(), tamper=True),
                make_jwt({"sub": "x", "aud": "minio-app",
                          "exp": time.time() + 60})):
        st, _ = sts_post(srv.port, {
            "Action": "AssumeRoleWithWebIdentity",
            "Version": "2011-06-15", "WebIdentityToken": bad})
        assert st == 403

    # ClientGrants uses the same validation over the Token field
    st, body = sts_post(srv.port, {
        "Action": "AssumeRoleWithClientGrants", "Version": "2011-06-15",
        "Token": make_jwt(claims(policy="readwrite"))})
    assert st == 200
    rw = Client(srv.port, parse_sts_creds(body))
    assert rw.request("PUT", "/fedbucket/w", body=b"x")[0] == 200


def test_e2e_ldap_identity(fed_server, ldap_server):
    srv, iam = fed_server
    srv.api.ldap_provider = LDAPProvider({
        "server_addr": f"127.0.0.1:{ldap_server.port}",
        "user_dn_format": "uid=%s,ou=people,dc=example,dc=org"})
    root = Client(srv.port, CREDS)
    assert root.request("PUT", "/ldapbucket")[0] == 200

    dn = "uid=bob,ou=people,dc=example,dc=org"
    # policy DB mapping for the DN, set by the admin (never the client)
    iam.attach_policy("readwrite", user=f"ldap:{dn}")

    st, body = sts_post(srv.port, {
        "Action": "AssumeRoleWithLDAPIdentity", "Version": "2011-06-15",
        "LDAPUsername": "bob", "LDAPPassword": "bobsecret"})
    assert st == 200, body
    temp = Client(srv.port, parse_sts_creds(body))
    assert temp.request("PUT", "/ldapbucket/o", body=b"ld")[0] == 200
    st, got = temp.request("GET", "/ldapbucket/o")
    assert st == 200 and got == b"ld"

    # bad password -> AccessDenied, nothing minted
    st, _ = sts_post(srv.port, {
        "Action": "AssumeRoleWithLDAPIdentity", "Version": "2011-06-15",
        "LDAPUsername": "bob", "LDAPPassword": "nope"})
    assert st == 403


def test_ldap_dn_injection_escaped(ldap_server):
    """A username containing DN metacharacters must be escaped (RFC
    4514), not allowed to inject DN structure and pick another DN's
    policy mapping (review r3)."""
    p = LDAPProvider({
        "server_addr": f"127.0.0.1:{ldap_server.port}",
        "user_dn_format": "uid=%s,ou=people,dc=example,dc=org"})
    with pytest.raises(STSValidationError):
        p.bind("bob,ou=admins", "bobsecret")
    assert ldap_server.binds[-1] == \
        "uid=bob\\,ou\\=admins,ou=people,dc=example,dc=org"


def test_ldap_fragmented_response():
    """BindResponse fragmented across TCP segments must still parse
    (length-driven read loop, review r3)."""
    s = FakeLDAPServer(
        {"uid=bob,ou=people,dc=example,dc=org": "bobsecret"},
        fragment=True)
    try:
        p = LDAPProvider({
            "server_addr": f"127.0.0.1:{s.port}",
            "user_dn_format": "uid=%s,ou=people,dc=example,dc=org"})
        assert p.bind("bob", "bobsecret").startswith("uid=bob")
    finally:
        s.close()


def test_minted_cred_capped_by_token_exp(fed_server):
    """Federated credentials must not outlive the JWT that minted them
    (review r3): a 7-day DurationSeconds with a 16-minute token yields
    a 16-minute credential."""
    srv, iam = fed_server
    tok = make_jwt(claims(exp=time.time() + 960, policy="readwrite"))
    st, body = sts_post(srv.port, {
        "Action": "AssumeRoleWithWebIdentity", "Version": "2011-06-15",
        "WebIdentityToken": tok, "DurationSeconds": "604800"})
    assert st == 200, body
    ns = {"sts": "https://sts.amazonaws.com/doc/2011-06-15/"}
    exp_s = ET.fromstring(body).find(".//sts:Expiration", ns).text
    import datetime as dt
    exp = dt.datetime.strptime(exp_s, "%Y-%m-%dT%H:%M:%SZ").replace(
        tzinfo=dt.timezone.utc).timestamp()
    assert exp <= time.time() + 961
