"""Web JSON-RPC control surface (reference cmd/web-handlers.go,
VERDICT r3 item 3): login→JWT, bucket/object RPCs with IAM
enforcement, URL tokens, presigned share URLs, upload/download web
paths, and the zip-of-prefix download — all over a live S3Server."""

from __future__ import annotations

import http.client
import io
import json
import time
import urllib.parse
import zipfile

import pytest

from minio_tpu.iam.sys import IAMSys
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.web import jwt_encode, mount
from tests.test_s3 import CREDS, REGION


@pytest.fixture(scope="module")
def web_server(tmp_path_factory):
    root = tmp_path_factory.mktemp("webdrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    iam = IAMSys(sets, root_cred=CREDS)
    srv = S3Server(sets, creds=CREDS, region=REGION, iam=iam).start()
    mount(srv)
    yield srv, iam
    srv.stop()
    sets.close()


def _call(port, method, params=None, token="", rid=1, path="/minio/webrpc"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    hdrs = {"Content-Type": "application/json"}
    if token:
        hdrs["Authorization"] = f"Bearer {token}"
    conn.request("POST", path, body=json.dumps(
        {"jsonrpc": "2.0", "id": rid, "method": f"Web.{method}",
         "params": params or {}}), headers=hdrs)
    resp = conn.getresponse()
    out = json.loads(resp.read())
    conn.close()
    return out


def _http(port, method, path, body=b"", headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    hdrs = {k.lower(): v for k, v in resp.getheaders()}
    conn.close()
    return resp.status, hdrs, data


def _login(port, user=None, pwd=None):
    out = _call(port, "Login", {"username": user or CREDS.access_key,
                                "password": pwd or CREDS.secret_key})
    assert "result" in out, out
    return out["result"]["token"]


def test_login_and_failure_modes(web_server):
    srv, _iam = web_server
    token = _login(srv.port)
    assert token.count(".") == 2

    # wrong password
    out = _call(srv.port, "Login", {"username": CREDS.access_key,
                                    "password": "nope"})
    assert out["error"]["code"] == 403
    # unknown user
    out = _call(srv.port, "Login", {"username": "ghost",
                                    "password": "whatever"})
    assert out["error"]["code"] == 403
    # no token on an authenticated method
    out = _call(srv.port, "ListBuckets")
    assert "error" in out
    # garbage token
    out = _call(srv.port, "ListBuckets", token="aa.bb.cc")
    assert "error" in out
    # token signed with the wrong secret
    forged = jwt_encode({"sub": CREDS.access_key, "typ": "web",
                         "exp": time.time() + 600}, "wrong-secret")
    out = _call(srv.port, "ListBuckets", token=forged)
    assert "error" in out
    # expired token
    expired = jwt_encode({"sub": CREDS.access_key, "typ": "web",
                          "exp": time.time() - 5}, CREDS.secret_key)
    out = _call(srv.port, "ListBuckets", token=expired)
    assert "error" in out
    # URL token must not work as a session token
    out = _call(srv.port, "CreateURLToken", token=token)
    url_token = out["result"]["token"]
    out = _call(srv.port, "ListBuckets", token=url_token)
    assert "error" in out
    # unknown method
    out = _call(srv.port, "NoSuchThing", token=token)
    assert out["error"]["code"] == -32601


def test_malformed_inputs_get_json_errors(web_server):
    """Review r4: non-object JSON bodies/params and hostile object keys
    must produce JSON-RPC errors / sanitized headers, never aborted
    connections or header injection."""
    srv, _iam = web_server
    token = _login(srv.port)
    # non-dict request body
    st, _, data = _http(srv.port, "POST", "/minio/webrpc", body=b"[1]",
                        headers={"Content-Type": "application/json"})
    assert st == 200 and json.loads(data)["error"]["code"] == -32600
    # non-dict params
    out = _call(srv.port, "ListBuckets", params=None, token=token)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    conn.request("POST", "/minio/webrpc", body=json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": "Web.ListBuckets",
         "params": "nope"}),
        headers={"Authorization": f"Bearer {token}"})
    resp = conn.getresponse()
    assert json.loads(resp.read())["error"]["code"] == -32602
    conn.close()
    # token with a non-dict payload segment
    bad = "e30.MTIz.e30"
    out = _call(srv.port, "ListBuckets", token=bad)
    assert "error" in out
    # a key with CRLF + quote must come back with sanitized
    # Content-Disposition (no header splitting)
    _call(srv.port, "MakeBucket", {"bucketName": "hostile"}, token=token)
    evil_key = 'a\r\nSet-Cookie: x="1'
    quoted = urllib.parse.quote(evil_key)
    st, _, _ = _http(srv.port, "PUT",
                     f"/minio/web/upload/hostile/{quoted}", body=b"v",
                     headers={"Authorization": f"Bearer {token}",
                              "Content-Length": "1"})
    assert st == 200
    st, hdrs, data = _http(
        srv.port, "GET",
        f"/minio/web/download/hostile/{quoted}?token={token}")
    assert st == 200 and data == b"v"
    assert "set-cookie" not in hdrs
    assert "\r" not in hdrs["content-disposition"]


def test_bucket_and_object_rpcs(web_server):
    srv, _iam = web_server
    token = _login(srv.port)
    assert "result" in _call(srv.port, "MakeBucket",
                             {"bucketName": "webbucket"}, token=token)
    names = [b["name"] for b in _call(
        srv.port, "ListBuckets", token=token)["result"]["buckets"]]
    assert "webbucket" in names

    # upload two objects over the web path
    st, hdrs, _ = _http(srv.port, "PUT",
                        "/minio/web/upload/webbucket/dir/a.txt",
                        body=b"alpha",
                        headers={"Authorization": f"Bearer {token}",
                                 "Content-Type": "text/plain",
                                 "Content-Length": "5"})
    assert st == 200 and hdrs.get("etag")
    st, _, _ = _http(srv.port, "PUT",
                     "/minio/web/upload/webbucket/dir/b.bin",
                     body=b"beta!",
                     headers={"Authorization": f"Bearer {token}",
                              "Content-Length": "5"})
    assert st == 200

    out = _call(srv.port, "ListObjects",
                {"bucketName": "webbucket", "prefix": "dir/"},
                token=token)["result"]
    assert [o["name"] for o in out["objects"]] == ["dir/a.txt",
                                                   "dir/b.bin"]

    # delimiter listing at the root shows the prefix
    out = _call(srv.port, "ListObjects", {"bucketName": "webbucket"},
                token=token)["result"]
    assert {o["name"] for o in out["objects"]} == {"dir/"}

    # download with a URL token (?token=, no headers)
    url_token = _call(srv.port, "CreateURLToken",
                      token=token)["result"]["token"]
    st, hdrs, data = _http(
        srv.port, "GET",
        f"/minio/web/download/webbucket/dir/a.txt?token={url_token}")
    assert st == 200 and data == b"alpha"
    assert "attachment" in hdrs.get("content-disposition", "")
    # no token -> denied
    st, _, _ = _http(srv.port, "GET",
                     "/minio/web/download/webbucket/dir/a.txt")
    assert st == 403

    # RemoveObject with a trailing-slash prefix removes recursively
    out = _call(srv.port, "RemoveObject",
                {"bucketName": "webbucket", "objects": ["dir/"]},
                token=token)
    assert "result" in out
    out = _call(srv.port, "ListObjects", {"bucketName": "webbucket"},
                token=token)["result"]
    assert out["objects"] == []


def test_zip_download_roundtrip(web_server):
    srv, _iam = web_server
    token = _login(srv.port)
    _call(srv.port, "MakeBucket", {"bucketName": "zipbucket"},
          token=token)
    payloads = {"docs/one.txt": b"one" * 1000,
                "docs/sub/two.txt": b"two" * 2000,
                "docs/three.bin": bytes(range(256)) * 64}
    for k, v in payloads.items():
        st, _, _ = _http(srv.port, "PUT",
                         f"/minio/web/upload/zipbucket/{k}", body=v,
                         headers={"Authorization": f"Bearer {token}",
                                  "Content-Length": str(len(v))})
        assert st == 200

    st, hdrs, data = _http(
        srv.port, "POST", f"/minio/web/zip?token={token}",
        body=json.dumps({"bucketName": "zipbucket", "prefix": "docs/",
                         "objects": [""]}).encode(),
        headers={"Content-Type": "application/json"})
    assert st == 200, data
    assert hdrs.get("content-type") == "application/zip"
    zf = zipfile.ZipFile(io.BytesIO(data))
    assert sorted(zf.namelist()) == ["one.txt", "sub/two.txt",
                                     "three.bin"] or \
        sorted(zf.namelist()) == sorted(
            k[len("docs/"):] for k in payloads)
    for k, v in payloads.items():
        assert zf.read(k[len("docs/"):]) == v

    # explicit object selection
    st, _, data = _http(
        srv.port, "POST", f"/minio/web/zip?token={token}",
        body=json.dumps({"bucketName": "zipbucket", "prefix": "docs/",
                         "objects": ["one.txt"]}).encode())
    assert st == 200
    zf = zipfile.ZipFile(io.BytesIO(data))
    assert zf.namelist() == ["one.txt"]


def test_iam_user_scoping_and_setauth(web_server):
    srv, iam = web_server
    root_token = _login(srv.port)
    _call(srv.port, "MakeBucket", {"bucketName": "rootonly"},
          token=root_token)

    iam.add_user("webuser", "webuser-secret-1")
    iam.attach_policy("readonly", user="webuser")
    utoken = _login(srv.port, "webuser", "webuser-secret-1")

    # readonly user: list allowed, create denied
    out = _call(srv.port, "ListBuckets", token=utoken)
    assert "result" in out
    out = _call(srv.port, "MakeBucket", {"bucketName": "userbucket"},
                token=utoken)
    assert out["error"]["code"] == 403
    # upload denied for readonly
    st, _, _ = _http(srv.port, "PUT",
                     "/minio/web/upload/rootonly/x",
                     body=b"x",
                     headers={"Authorization": f"Bearer {utoken}",
                              "Content-Length": "1"})
    assert st == 403

    # owner can't SetAuth, user can; old token dies with the rotation
    out = _call(srv.port, "SetAuth",
                {"currentSecretKey": CREDS.secret_key,
                 "newSecretKey": "irrelevant1"}, token=root_token)
    assert out["error"]["code"] == 403
    out = _call(srv.port, "SetAuth",
                {"currentSecretKey": "wrong",
                 "newSecretKey": "newsecret99"}, token=utoken)
    assert out["error"]["code"] == 403
    out = _call(srv.port, "SetAuth",
                {"currentSecretKey": "webuser-secret-1",
                 "newSecretKey": "newsecret99"}, token=utoken)
    assert "result" in out, out
    new_token = out["result"]["token"]
    assert "result" in _call(srv.port, "ListBuckets", token=new_token)
    # the pre-rotation token no longer verifies
    out = _call(srv.port, "ListBuckets", token=utoken)
    assert "error" in out
    assert _login(srv.port, "webuser", "newsecret99")


def test_web_download_transformed_objects(web_server):
    """ADVICE r4: web download/zip must route through the same
    SSE/compression seam as the S3 GET path — a compressed or SSE-S3
    object downloads as plaintext with the plaintext Content-Length;
    SSE-C downloads are rejected (no client key headers on a browser
    navigation)."""
    import hashlib
    import os
    from minio_tpu.features import crypto as sse
    from minio_tpu.features.kms import StaticKMS
    from minio_tpu.object.engine import PutOptions
    from minio_tpu.object.hash_reader import HashReader

    srv, _iam = web_server
    token = _login(srv.port)
    _call(srv.port, "MakeBucket", {"bucketName": "xform"}, token=token)
    old_kms = srv.api.kms
    srv.api.kms = StaticKMS(hashlib.sha256(b"web-master").digest())
    try:
        payload = b"web-plaintext " * 4096

        def put(key, ssec_key=None, sse_s3=False, compress=False):
            md = {}
            reader, size = sse.setup_put_transforms(
                key_name=key,
                raw_reader=HashReader(io.BytesIO(payload), len(payload)),
                raw_size=len(payload), metadata=md, ssec_key=ssec_key,
                sse_s3=sse_s3, kms=srv.api.kms, compress=compress)
            srv.api.obj.put_object("xform", key, reader, size,
                                   PutOptions(metadata=md))

        put("comp.txt", compress=True)
        put("enc.txt", sse_s3=True)
        put("both.txt", sse_s3=True, compress=True)
        put("ssec.txt", ssec_key=os.urandom(32))

        for k in ("comp.txt", "enc.txt", "both.txt"):
            st, hdrs, data = _http(
                srv.port, "GET",
                f"/minio/web/download/xform/{k}?token={token}")
            assert st == 200 and data == payload, k
            assert hdrs["content-length"] == str(len(payload))
        st, _, _ = _http(
            srv.port, "GET",
            f"/minio/web/download/xform/ssec.txt?token={token}")
        assert st == 403

        # the zip path decodes through the same seam
        st, _, data = _http(
            srv.port, "POST", f"/minio/web/zip?token={token}",
            body=json.dumps({"bucketName": "xform", "prefix": "",
                             "objects": ["comp.txt",
                                         "enc.txt"]}).encode())
        assert st == 200
        zf = zipfile.ZipFile(io.BytesIO(data))
        assert zf.read("comp.txt") == payload
        assert zf.read("enc.txt") == payload
    finally:
        srv.api.kms = old_kms


def test_url_token_scope_and_malformed_exp(web_server):
    """ADVICE r4: CreateURLToken tokens must not authorize uploads, and
    a token with a non-numeric exp claim is AccessDenied, not a 500."""
    srv, _iam = web_server
    token = _login(srv.port)
    _call(srv.port, "MakeBucket", {"bucketName": "scope"}, token=token)
    url_token = _call(srv.port, "CreateURLToken",
                      token=token)["result"]["token"]
    st, _, _ = _http(srv.port, "PUT", "/minio/web/upload/scope/x",
                     body=b"x",
                     headers={"Authorization": f"Bearer {url_token}",
                              "Content-Length": "1"})
    assert st == 403
    st, _, _ = _http(srv.port, "PUT", "/minio/web/upload/scope/x",
                     body=b"x",
                     headers={"Authorization": f"Bearer {token}",
                              "Content-Length": "1"})
    assert st == 200
    # the url token's actual purpose still works
    st, _, data = _http(
        srv.port, "GET",
        f"/minio/web/download/scope/x?token={url_token}")
    assert st == 200 and data == b"x"
    bad = jwt_encode({"sub": CREDS.access_key, "typ": "web",
                      "exp": "never"}, CREDS.secret_key)
    out = _call(srv.port, "ListBuckets", token=bad)
    assert "error" in out
    assert out["error"].get("code") != 500


def test_presigned_get_and_policy_rpcs(web_server):
    srv, _iam = web_server
    token = _login(srv.port)
    _call(srv.port, "MakeBucket", {"bucketName": "sharebucket"},
          token=token)
    st, _, _ = _http(srv.port, "PUT",
                     "/minio/web/upload/sharebucket/shared.txt",
                     body=b"shared-payload",
                     headers={"Authorization": f"Bearer {token}",
                              "Content-Length": "14"})
    assert st == 200

    out = _call(srv.port, "PresignedGet",
                {"bucketName": "sharebucket",
                 "objectName": "shared.txt",
                 "hostName": f"127.0.0.1:{srv.port}", "expiry": 3600},
                token=token)["result"]
    url = out["url"]
    # the presigned URL works unauthenticated over plain HTTP
    path = url.split(str(srv.port), 1)[1]
    st, _, data = _http(srv.port, "GET", path)
    assert st == 200 and data == b"shared-payload"

    # canned bucket policy set + readback
    out = _call(srv.port, "SetBucketPolicy",
                {"bucketName": "sharebucket", "prefix": "",
                 "policy": "readonly"}, token=token)
    assert "result" in out
    out = _call(srv.port, "GetBucketPolicy",
                {"bucketName": "sharebucket", "prefix": ""},
                token=token)["result"]
    assert out["policy"] == "readonly"
    out = _call(srv.port, "ListAllBucketPolicies",
                {"bucketName": "sharebucket"}, token=token)["result"]
    assert {"prefix": "sharebucket/*", "policy": "readonly"} in \
        out["policies"]
    # anonymous GET now allowed by the bucket policy
    st, _, data = _http(srv.port, "GET", "/sharebucket/shared.txt")
    assert st == 200 and data == b"shared-payload"
    # back to none
    _call(srv.port, "SetBucketPolicy",
          {"bucketName": "sharebucket", "prefix": "", "policy": "none"},
          token=token)
    out = _call(srv.port, "GetBucketPolicy",
                {"bucketName": "sharebucket", "prefix": ""},
                token=token)["result"]
    assert out["policy"] == "none"
    st, _, _ = _http(srv.port, "GET", "/sharebucket/shared.txt")
    assert st == 403
