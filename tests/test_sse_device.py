"""Device-fused SSE data path: engine PUT byte-identity vs the CPU
cipher oracle, cross-request coalescing of encrypted PUTs, fallback
discipline (knob off / deviceless / dispatch error), host-side tag
authentication of device output, and cross-path e2e (device-written
read by CPU and vice versa) over the live S3 server."""

from __future__ import annotations

import base64
import hashlib
import http.client
import io
import os
import threading
import urllib.parse

import numpy as np
import pytest

from minio_tpu.features import crypto as sse
from minio_tpu.object import ErasureSetObjects
from minio_tpu.object import codec as codec_mod
from minio_tpu.object import engine as engine_mod
from minio_tpu.ops import chacha20_ref as c20
from minio_tpu.parallel.scheduler import BatchScheduler
from minio_tpu.storage import XLStorage, new_format_erasure_v3

K, M = 4, 2
NDISKS = K + M
BLOCK = 1 << 16
PKG = sse.PKG_SIZE


@pytest.fixture
def device_on(monkeypatch):
    """Run the device route on the CPU JAX backend: the fused programs
    jit and execute identically; only placement differs."""
    monkeypatch.setattr(codec_mod, "_IS_TPU", True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE_MIN_BYTES", "0")
    monkeypatch.setenv("MINIO_TPU_SSE_CIPHER", "chacha20")


def make_engine(tmp_path, sub="", scheduler=None):
    fmts = new_format_erasure_v3(1, NDISKS)
    disks = []
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"{sub}d{j}"))
        d.write_format(fmts[0][j])
        disks.append(d)
    e = ErasureSetObjects(disks, K, M, block_size=BLOCK,
                          scheduler=scheduler)
    e.make_bucket("b")
    return e


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def read_stored(eng, name):
    _, it = eng.get_object("b", name)
    return b"".join(it)


def cpu_oracle(pt, oek, base):
    enc = sse.ChaChaEncryptor(oek, base)
    return enc.update(pt) + enc.finalize()


OEK = bytes(range(32))
BASE = bytes(range(100, 112))


# ---------------------------------------------------------------------------
# engine PUT byte-identity: fused device path == CPU cipher oracle
# ---------------------------------------------------------------------------

def test_fused_put_stored_bytes_match_cpu_oracle(tmp_path, device_on):
    eng = make_engine(tmp_path)
    assert eng.supports_sse_device
    for i, n in enumerate((0, 100, BLOCK, 3 * BLOCK + 17)):
        pt = payload(n, seed=i)
        opts = engine_mod.PutOptions(sse_spec=sse.DeviceSSE(OEK, BASE))
        info = eng.put_object("b", f"o{i}", pt, opts=opts)
        want = cpu_oracle(pt, OEK, BASE)
        assert info.size == sse.encrypted_size(n)
        assert read_stored(eng, f"o{i}") == want, n


def test_fused_put_pipelined_unknown_size(tmp_path, device_on):
    eng = make_engine(tmp_path)
    n = 5 * BLOCK + PKG + 123
    pt = payload(n, seed=42)
    opts = engine_mod.PutOptions(sse_spec=sse.DeviceSSE(OEK, BASE))
    eng.put_object("b", "o", io.BytesIO(pt), size=-1, opts=opts)
    assert read_stored(eng, "o") == cpu_oracle(pt, OEK, BASE)


def test_fused_put_through_scheduler(tmp_path, device_on):
    sched = BatchScheduler()
    try:
        eng = make_engine(tmp_path, scheduler=sched)
        n = 2 * BLOCK + 99
        pt = payload(n, seed=3)
        opts = engine_mod.PutOptions(sse_spec=sse.DeviceSSE(OEK, BASE))
        eng.put_object("b", "o", pt, opts=opts)
        assert read_stored(eng, "o") == cpu_oracle(pt, OEK, BASE)
        assert sched.verb_stats["encode"]["batches"] >= 1
    finally:
        sched.close()


def test_device_tags_verify_with_scalar_reference(tmp_path, device_on):
    """No laundered auth: the trailer committed by the DEVICE path must
    open every package under the independent scalar AEAD reference —
    the tags were computed host-side over the ciphertext actually
    written, before commit."""
    eng = make_engine(tmp_path)
    n = 2 * BLOCK + 500
    pt = payload(n, seed=9)
    eng.put_object("b", "o", pt,
                   opts=engine_mod.PutOptions(
                       sse_spec=sse.DeviceSSE(OEK, BASE)))
    stored = read_stored(eng, "o")
    ct_len, npkg = sse.chacha_ct_len(len(stored))
    assert ct_len == n
    got = b""
    for seq in range(npkg):
        pkg_ct = stored[seq * PKG:min((seq + 1) * PKG, ct_len)]
        tag = stored[ct_len + seq * 16:ct_len + (seq + 1) * 16]
        got += c20.open_detached(OEK, sse._pkg_nonce(BASE, seq),
                                 sse._pkg_aad(seq), pkg_ct, tag)
    assert got == pt


# ---------------------------------------------------------------------------
# coalescing: concurrent encrypted PUTs under DIFFERENT keys share a launch
# ---------------------------------------------------------------------------

def test_two_encrypted_puts_coalesce_into_one_launch(device_on):
    sched = BatchScheduler(max_wait=0.2)
    codec = codec_mod.Codec(K, M, BLOCK)
    rng = np.random.default_rng(21)
    specs = [sse.DeviceSSE(rng.bytes(32), rng.bytes(12))
             for _ in range(2)]
    datas = [rng.integers(0, 256, (2, K, codec.shard_size),
                          dtype=np.uint8) for _ in range(2)]
    try:
        # warm the jit cache so the counter window isn't skewed by
        # compile time
        w = specs[0].batch_params(0, 2, BLOCK)
        sched.submit(codec, datas[0], engine_mod.bitrot_mod
                     .BitrotAlgorithm.HIGHWAYHASH256,
                     sse=(w[0], w[1], PKG)).result()
        b0, c0 = sched.batches, sched.coalesced
        barrier = threading.Barrier(2)
        outs = [None, None]

        def put(i):
            kn = specs[i].batch_params(0, 2, BLOCK)
            barrier.wait()
            fut = sched.submit(
                codec, datas[i],
                engine_mod.bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256,
                sse=(kn[0], kn[1], PKG))
            outs[i] = fut.result()

        ts = [threading.Thread(target=put, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sched.batches - b0 == 1, "expected ONE shared dispatch"
        assert sched.coalesced - c0 == 1
        # each object's rows deciphered under its OWN key round-trip
        for i in range(2):
            full, _dig = outs[i]
            flat = np.ascontiguousarray(
                full[:, :K]).reshape(2, -1)[:, :BLOCK].copy()
            specs_pt = flat.copy()
            specs[i].cpu_encrypt_rows(specs_pt, 0)   # XOR twice = undo
            assert specs_pt.tobytes() == \
                datas[i].reshape(2, -1)[:, :BLOCK].tobytes()
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# fallback discipline
# ---------------------------------------------------------------------------

def test_knob_off_disables_device_path(monkeypatch, device_on):
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE", "off")
    assert not sse.device_sse_allowed(1 << 20)


def test_deviceless_declines(monkeypatch):
    monkeypatch.setattr(codec_mod, "_IS_TPU", False)
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE_MIN_BYTES", "0")
    assert not sse.device_sse_allowed(1 << 20)


def test_size_window_gates(monkeypatch, device_on):
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE_MIN_BYTES", str(1 << 20))
    assert not sse.device_sse_allowed((1 << 20) - 1)
    assert sse.device_sse_allowed(1 << 20)
    assert not sse.device_sse_allowed(-1)    # unknown size: CPU path
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE_MAX_BYTES", str(1 << 21))
    assert not sse.device_sse_allowed(1 << 22)


def test_dispatch_error_falls_back_to_cpu_cipher(tmp_path, device_on,
                                                 monkeypatch):
    """ANY device dispatch error must drop the batch to the in-place
    CPU cipher — stored bytes stay byte-identical to the oracle."""
    def boom(self, *a, **k):
        raise RuntimeError("injected dispatch failure")
    monkeypatch.setattr(codec_mod.Codec, "encrypt_encode_and_hash_batch",
                        boom)
    eng = make_engine(tmp_path)
    n = 2 * BLOCK + 1234
    pt = payload(n, seed=5)
    eng.put_object("b", "o", pt,
                   opts=engine_mod.PutOptions(
                       sse_spec=sse.DeviceSSE(OEK, BASE)))
    assert read_stored(eng, "o") == cpu_oracle(pt, OEK, BASE)


def test_dispatch_error_through_scheduler_falls_back(tmp_path, device_on,
                                                     monkeypatch):
    def boom(self, *a, **k):
        raise RuntimeError("injected dispatch failure")
    monkeypatch.setattr(codec_mod.Codec, "encrypt_encode_and_hash_batch",
                        boom)
    sched = BatchScheduler()
    try:
        eng = make_engine(tmp_path, scheduler=sched)
        pt = payload(BLOCK + 77, seed=6)
        eng.put_object("b", "o", pt,
                       opts=engine_mod.PutOptions(
                           sse_spec=sse.DeviceSSE(OEK, BASE)))
        assert read_stored(eng, "o") == cpu_oracle(pt, OEK, BASE)
    finally:
        sched.close()


def test_setup_put_transforms_gates_spec(monkeypatch, device_on):
    """spec only when chacha + device_sse + gate; otherwise the cipher
    stays a CPU transform and the stream carries ciphertext."""
    from minio_tpu.features.kms import StaticKMS
    from minio_tpu.object.hash_reader import HashReader
    kms = StaticKMS(hashlib.sha256(b"m").digest())

    def setup(**over):
        md = {}
        kw = dict(key_name="k", raw_reader=HashReader(io.BytesIO(b"x"), 1),
                  raw_size=1, metadata=md, ssec_key=None, sse_s3=True,
                  kms=kms, compress=False, device_sse=True)
        kw.update(over)
        return sse.setup_put_transforms(**kw), md

    (_, size, spec), md = setup()
    assert isinstance(spec, sse.DeviceSSE)
    assert size == sse.encrypted_size(1)
    assert md[sse.MK_CIPHER] == sse.CIPHER_CHACHA

    (_, _, spec), _ = setup(device_sse=False)
    assert spec is None
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE", "off")
    (_, _, spec), _ = setup()
    assert spec is None
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE", "on")
    monkeypatch.setenv("MINIO_TPU_SSE_CIPHER", "aes-gcm")
    try:
        (_, _, spec), _ = setup()
    except ModuleNotFoundError:
        pytest.skip("cryptography not installed: AES seal path "
                    "environmentally untestable")
    assert spec is None


# ---------------------------------------------------------------------------
# cross-path e2e over the live S3 server
# ---------------------------------------------------------------------------

from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("ssedevkey1", "ssedevsecret1")
REGION = "us-east-1"


@pytest.fixture()
def server(tmp_path):
    sets = ErasureSets.from_drives(
        [str(tmp_path / f"d{i}") for i in range(NDISKS)],
        set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    from minio_tpu.features.kms import StaticKMS
    srv.api.kms = StaticKMS(hashlib.sha256(b"m").digest())
    yield srv
    srv.stop()
    sets.close()


def _req(srv, method, path, query=None, body=b"", headers=None):
    query = {k: [v] for k, v in (query or {}).items()}
    qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
    hdrs = {k.lower(): v for k, v in (headers or {}).items()}
    hdrs["host"] = f"127.0.0.1:{srv.port}"
    ph = hashlib.sha256(body).hexdigest()
    hdrs = sig.sign_v4(method, urllib.parse.quote(path), query, hdrs,
                       ph, CREDS, REGION)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    conn.request(method, urllib.parse.quote(path) +
                 (f"?{qs}" if qs else ""), body=body, headers=hdrs)
    r = conn.getresponse()
    data = r.read()
    out = {k.lower(): v for k, v in r.getheaders()}
    conn.close()
    return r.status, out, data


def test_device_written_cpu_read_and_vice_versa(server, device_on,
                                                monkeypatch):
    st, _, _ = _req(server, "PUT", "/xbb")
    assert st == 200
    pt = payload(2 * BLOCK + 4321, seed=8)
    enc_hdr = {"x-amz-server-side-encryption": "AES256"}

    # device-fused PUT …
    st, _, _ = _req(server, "PUT", "/xbb/dev", body=pt, headers=enc_hdr)
    assert st == 200
    # … read back through the pure-CPU decrypt path
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE", "off")
    st, _, got = _req(server, "GET", "/xbb/dev")
    assert st == 200 and got == pt
    st, _, got = _req(server, "GET", "/xbb/dev",
                      headers={"range": f"bytes={PKG + 10}-{PKG + 200}"})
    assert st == 206 and got == pt[PKG + 10:PKG + 201]

    # CPU-transform PUT (device off) …
    st, _, _ = _req(server, "PUT", "/xbb/cpu", body=pt, headers=enc_hdr)
    assert st == 200
    # … read back with the device decipher batches enabled
    monkeypatch.setenv("MINIO_TPU_SSE_DEVICE", "on")
    st, _, got = _req(server, "GET", "/xbb/cpu")
    assert st == 200 and got == pt


def test_ssec_chacha_over_server(server, device_on):
    st, _, _ = _req(server, "PUT", "/xbb")
    assert st == 200
    key = os.urandom(32)
    hdrs = {
        "x-amz-server-side-encryption-customer-algorithm": "AES256",
        "x-amz-server-side-encryption-customer-key":
            base64.b64encode(key).decode(),
        "x-amz-server-side-encryption-customer-key-md5":
            base64.b64encode(hashlib.md5(key).digest()).decode(),
    }
    pt = payload(BLOCK + 999, seed=10)
    st, _, _ = _req(server, "PUT", "/xbb/sc", body=pt, headers=hdrs)
    assert st == 200
    st, _, got = _req(server, "GET", "/xbb/sc", headers=hdrs)
    assert st == 200 and got == pt
    st, _, _ = _req(server, "GET", "/xbb/sc")
    assert st in (400, 403)
